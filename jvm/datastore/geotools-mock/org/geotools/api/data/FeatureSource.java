package org.geotools.api.data;

import java.io.IOException;
import org.geotools.geometry.jts.ReferencedEnvelope;

/** Mock subset of {@code org.geotools.api.data.FeatureSource}. */
public interface FeatureSource<T, F> {
    T getSchema();
    DataStore getDataStore();
    ReferencedEnvelope getBounds() throws IOException;
    ReferencedEnvelope getBounds(Query query) throws IOException;
    int getCount(Query query) throws IOException;
}
