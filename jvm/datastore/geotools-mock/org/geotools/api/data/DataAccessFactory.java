package org.geotools.api.data;

import java.io.IOException;
import java.util.Map;

/** Mock subset of {@code org.geotools.api.data.DataAccessFactory}. */
public interface DataAccessFactory {
    String getDisplayName();
    String getDescription();
    Param[] getParametersInfo();
    boolean canProcess(Map<String, ?> params);
    boolean isAvailable();

    /** Connection parameter descriptor (subset of the real Param). */
    class Param {
        public final String key;
        public final Class<?> type;
        public final String description;
        public final boolean required;
        public final Object sample;

        public Param(String key, Class<?> type, String description,
                     boolean required) {
            this(key, type, description, required, null);
        }

        public Param(String key, Class<?> type, String description,
                     boolean required, Object sample) {
            this.key = key;
            this.type = type;
            this.description = description;
            this.required = required;
            this.sample = sample;
        }

        public Object lookUp(Map<String, ?> params) throws IOException {
            Object v = params.get(key);
            if (v == null && required) {
                throw new IOException("missing required parameter " + key);
            }
            return v;
        }
    }
}
