package org.geotools.api.data;

import java.io.Closeable;
import java.io.IOException;

/** Mock subset of {@code org.geotools.api.data.FeatureWriter}. */
public interface FeatureWriter<T, F> extends Closeable {
    T getFeatureType();
    F next() throws IOException;
    void remove() throws IOException;
    void write() throws IOException;
    boolean hasNext() throws IOException;
    @Override void close() throws IOException;
}
