package org.geotools.api.data;

import java.io.IOException;
import java.util.Map;

/** Mock subset of {@code org.geotools.api.data.DataStoreFactorySpi} —
 * the SPI the reference registers via META-INF/services
 * (geomesa-accumulo-datastore/src/main/resources/META-INF/services/
 * org.geotools.data.DataStoreFactorySpi; the package moved to
 * org.geotools.api.data in GeoTools 30). */
public interface DataStoreFactorySpi extends DataAccessFactory {
    DataStore createDataStore(Map<String, ?> params) throws IOException;
    DataStore createNewDataStore(Map<String, ?> params) throws IOException;
}
