package org.geotools.api.data;

/** Mock subset of {@code org.geotools.api.data.ServiceInfo}. */
public interface ServiceInfo {
    String getTitle();
    String getDescription();
}
