package org.geotools.api.data;

import java.io.IOException;
import java.util.List;
import org.geotools.api.feature.type.Name;

/** Mock subset of {@code org.geotools.api.data.DataAccess}. */
public interface DataAccess<T, F> {
    ServiceInfo getInfo();
    void createSchema(T featureType) throws IOException;
    void updateSchema(Name typeName, T featureType) throws IOException;
    void removeSchema(Name typeName) throws IOException;
    List<Name> getNames() throws IOException;
    T getSchema(Name name) throws IOException;
    FeatureSource<T, F> getFeatureSource(Name typeName) throws IOException;
    void dispose();
}
