package org.geotools.api.data;

/** Mock subset of {@code org.geotools.api.data.Transaction}. */
public interface Transaction {
    Transaction AUTO_COMMIT = new Transaction() {
        @Override public String toString() { return "AUTO_COMMIT"; }
    };
}
