package org.geotools.api.data;

import java.io.IOException;
import org.geotools.api.feature.simple.SimpleFeature;
import org.geotools.api.feature.simple.SimpleFeatureType;
import org.geotools.api.filter.Filter;

/** Mock subset of {@code org.geotools.api.data.DataStore} — the method
 * set the reference's GeoMesaDataStore implements
 * (geomesa-index-api/.../geotools/GeoMesaDataStore.scala:49). */
public interface DataStore extends DataAccess<SimpleFeatureType, SimpleFeature> {
    void updateSchema(String typeName, SimpleFeatureType featureType)
            throws IOException;
    void removeSchema(String typeName) throws IOException;
    String[] getTypeNames() throws IOException;
    SimpleFeatureType getSchema(String typeName) throws IOException;
    SimpleFeatureSource getFeatureSource(String typeName) throws IOException;
    FeatureReader<SimpleFeatureType, SimpleFeature> getFeatureReader(
            Query query, Transaction transaction) throws IOException;
    FeatureWriter<SimpleFeatureType, SimpleFeature> getFeatureWriter(
            String typeName, Filter filter, Transaction transaction)
            throws IOException;
    FeatureWriter<SimpleFeatureType, SimpleFeature> getFeatureWriter(
            String typeName, Transaction transaction) throws IOException;
    FeatureWriter<SimpleFeatureType, SimpleFeature> getFeatureWriterAppend(
            String typeName, Transaction transaction) throws IOException;
    LockingManager getLockingManager();
}
