package org.geotools.geometry.jts;

/** Mock subset of {@code org.geotools.geometry.jts.ReferencedEnvelope}
 * (CRS is fixed to EPSG:4326 in this transport). */
public class ReferencedEnvelope {
    private final double minX, minY, maxX, maxY;

    public ReferencedEnvelope(double minX, double maxX,
                              double minY, double maxY) {
        this.minX = minX; this.maxX = maxX;
        this.minY = minY; this.maxY = maxY;
    }

    public double getMinX() { return minX; }
    public double getMaxX() { return maxX; }
    public double getMinY() { return minY; }
    public double getMaxY() { return maxY; }

    @Override public String toString() {
        return "[" + minX + ", " + minY + ", " + maxX + ", " + maxY + "]";
    }
}
