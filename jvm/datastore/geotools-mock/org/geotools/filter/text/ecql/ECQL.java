package org.geotools.filter.text.ecql;

import org.geotools.api.filter.Filter;

/** Mock of gt-cql's {@code ECQL}: filters carry their ECQL text
 * verbatim (the real class parses/serializes the filter model). */
public final class ECQL {
    private ECQL() {}

    private static final class TextFilter implements Filter {
        private final String ecql;
        TextFilter(String ecql) { this.ecql = ecql; }
        @Override public String toString() { return ecql; }
    }

    public static Filter toFilter(String ecql) { return new TextFilter(ecql); }

    public static String toCQL(Filter filter) {
        return filter == null ? "INCLUDE" : filter.toString();
    }
}
