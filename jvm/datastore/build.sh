#!/bin/sh
# Compile the GeoTools DataStore module + smoke runner against the
# vendored interface mock (no third-party jars needed; JDK 11+).
#
# To compile against real GeoTools instead, drop the geotools-mock
# sourcepath and put gt-api/gt-cql/gt-referencing jars on -cp.
#
#   ./build.sh            # compile into out/
#   geomesa-tpu web --port 8080 &
#   java -cp out Smoke http://127.0.0.1:8080
set -e
cd "$(dirname "$0")"
rm -rf out
mkdir -p out
javac -d out \
    $(find geotools-mock -name '*.java') \
    $(find src/main/java -name '*.java') \
    smoke/Smoke.java
cp -r src/main/resources/META-INF out/
echo "compiled to out/; run: java -cp out Smoke <rest-url>"
