import java.util.HashMap;
import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;
import org.geotools.api.data.DataStore;
import org.geotools.api.data.DataStoreFinder;
import org.geotools.api.data.FeatureReader;
import org.geotools.api.data.FeatureWriter;
import org.geotools.api.data.Query;
import org.geotools.api.data.SimpleFeatureSource;
import org.geotools.api.data.Transaction;
import org.geotools.api.feature.simple.SimpleFeature;
import org.geotools.api.feature.simple.SimpleFeatureType;
import org.geotools.filter.text.ecql.ECQL;
import org.geotools.geometry.jts.ReferencedEnvelope;
import org.locationtech.geomesa.tpu.geotools.GeoMesaTpuDataStoreFactory;
import org.locationtech.geomesa.tpu.geotools.SimpleFeatureTypes;

/**
 * End-to-end smoke for the GeoTools DataStore module:
 * DataStoreFinder resolves the factory from META-INF/services, then the
 * full lifecycle round-trips through a live geomesa-tpu REST server:
 * createSchema -> writer append -> count/bounds via stats -> filtered
 * read -> removeSchema -> dispose.
 *
 * <pre>
 *   geomesa-tpu web --port 8080 &amp;
 *   java -cp out Smoke http://127.0.0.1:8080
 * </pre>
 */
public final class Smoke {
    private Smoke() {}

    private static void check(boolean ok, String what) {
        if (!ok) throw new AssertionError("FAILED: " + what);
        System.out.println("ok: " + what);
    }

    public static void main(String[] args) throws Exception {
        String url = args.length > 0 ? args[0] : "http://127.0.0.1:8080";
        Map<String, Object> params = new HashMap<>();
        params.put(GeoMesaTpuDataStoreFactory.REST_URL_PARAM.key, url);

        DataStore store = DataStoreFinder.getDataStore(params);
        check(store != null,
                "DataStoreFinder resolved the factory via META-INF/services");

        String typeName = "smoke_" + (System.nanoTime() % 1000000);
        SimpleFeatureType sft = SimpleFeatureTypes.createType(typeName,
                "name:String,age:Integer,dtg:Date,*geom:Point:srid=4326");
        store.createSchema(sft);
        SimpleFeatureType fetched = store.getSchema(typeName);
        check(fetched.getAttributeNames().contains("age")
                        && "geom".equals(fetched.getGeometryAttribute()),
                "schema round-trip through the server catalog");

        try (FeatureWriter<SimpleFeatureType, SimpleFeature> writer =
                     store.getFeatureWriterAppend(
                             typeName, Transaction.AUTO_COMMIT)) {
            for (int i = 0; i < 10; i++) {
                SimpleFeature f = writer.next();
                f.setAttribute("name", i % 2 == 0 ? "even" : "odd");
                f.setAttribute("age", i);
                f.setAttribute("dtg", "2020-01-05T00:00:00");
                Map<String, Object> geom = new LinkedHashMap<>();
                geom.put("type", "Point");
                geom.put("coordinates", List.of((double) i, 1.0));
                f.setAttribute("geom", geom);
                writer.write();
            }
        }

        SimpleFeatureSource source = store.getFeatureSource(typeName);
        check(source.getCount(new Query(typeName)) == 10,
                "count via server stats == 10");
        ReferencedEnvelope bounds = source.getBounds();
        check(bounds != null && bounds.getMinX() == 0.0
                        && bounds.getMaxX() == 9.0,
                "bounds via server stats == [0, 9] x [1, 1]");

        Query q = new Query(typeName,
                ECQL.toFilter("age > 4 AND BBOX(geom, -1, 0, 20, 2)"));
        int hits = 0;
        boolean sawGeometry = false;
        try (FeatureReader<SimpleFeatureType, SimpleFeature> reader =
                     store.getFeatureReader(q, Transaction.AUTO_COMMIT)) {
            while (reader.hasNext()) {
                SimpleFeature f = reader.next();
                hits++;
                sawGeometry |= f.getDefaultGeometry() != null;
                check(((Number) f.getAttribute("age")).intValue() > 4,
                        "filter pushdown honored for " + f.getID());
            }
        }
        check(hits == 5 && sawGeometry,
                "filtered read returned 5 features with geometries");

        store.removeSchema(typeName);
        boolean gone = true;
        for (String n : store.getTypeNames()) {
            gone &= !n.equals(typeName);
        }
        check(gone, "removeSchema dropped the type");
        store.dispose();
        System.out.println("SMOKE PASSED against " + url);
    }
}
