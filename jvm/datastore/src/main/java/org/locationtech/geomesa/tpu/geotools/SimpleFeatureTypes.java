package org.locationtech.geomesa.tpu.geotools;

import org.geotools.api.feature.simple.SimpleFeatureType;

/**
 * Spec-string SimpleFeatureType builder — the analog of the reference's
 * {@code SimpleFeatureTypes.createType}
 * (geomesa-utils/.../geotools/SimpleFeatureTypes.scala), kept
 * format-compatible so GeoMesa specs carry over verbatim:
 *
 * <pre>
 *   SimpleFeatureTypes.createType("gdelt",
 *       "name:String,dtg:Date,*geom:Point:srid=4326");
 * </pre>
 */
public final class SimpleFeatureTypes {
    private SimpleFeatureTypes() {}

    public static SimpleFeatureType createType(String typeName, String spec) {
        return new TpuSimpleFeatureType(typeName, spec);
    }

    /** The spec string for a type created by {@link #createType} (or
     * fetched from a geomesa-tpu server). */
    public static String encodeType(SimpleFeatureType type) {
        if (type instanceof TpuSimpleFeatureType) {
            return ((TpuSimpleFeatureType) type).getSpec();
        }
        throw new IllegalArgumentException(
                "not a geomesa-tpu feature type: " + type);
    }
}
