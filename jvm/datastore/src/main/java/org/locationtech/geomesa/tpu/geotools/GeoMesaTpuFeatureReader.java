package org.locationtech.geomesa.tpu.geotools;

import java.io.IOException;
import java.util.Iterator;
import java.util.List;
import java.util.Map;
import java.util.NoSuchElementException;
import org.geotools.api.data.FeatureReader;
import org.geotools.api.feature.simple.SimpleFeature;
import org.geotools.api.feature.simple.SimpleFeatureType;

/**
 * FeatureReader over the REST transport's GeoJSON FeatureCollection —
 * the analog of the reference's reader over the QueryPlan's scan
 * (geomesa-index-api/.../planning/QueryPlanner.scala runQuery results).
 */
final class GeoMesaTpuFeatureReader
        implements FeatureReader<SimpleFeatureType, SimpleFeature> {

    private final TpuSimpleFeatureType type;
    private final Iterator<Object> features;

    @SuppressWarnings("unchecked")
    GeoMesaTpuFeatureReader(TpuSimpleFeatureType type,
                            Map<String, Object> featureCollection) {
        this.type = type;
        Object f = featureCollection.get("features");
        this.features = ((List<Object>) f).iterator();
    }

    @Override public SimpleFeatureType getFeatureType() { return type; }

    @Override public boolean hasNext() { return features.hasNext(); }

    @Override
    @SuppressWarnings("unchecked")
    public SimpleFeature next() throws NoSuchElementException {
        Map<String, Object> f = (Map<String, Object>) features.next();
        Map<String, Object> props = (Map<String, Object>) f.get("properties");
        return new TpuSimpleFeature(
                type,
                String.valueOf(f.get("id")),
                f.get("geometry"),
                props == null ? Map.of() : props);
    }

    @Override public void close() throws IOException {
        // the collection is fully materialized by the transport;
        // nothing to release
    }
}
