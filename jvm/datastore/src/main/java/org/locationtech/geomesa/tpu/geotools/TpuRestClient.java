package org.locationtech.geomesa.tpu.geotools;

import java.io.IOException;
import java.net.URI;
import java.net.URLEncoder;
import java.net.http.HttpClient;
import java.net.http.HttpRequest;
import java.net.http.HttpResponse;
import java.nio.charset.StandardCharsets;
import java.time.Duration;
import java.util.List;
import java.util.Map;

/**
 * JDK-only transport for the geomesa-tpu REST surface
 * (geomesa_tpu/web.py). Endpoint contract (CI-verified by
 * tests/test_jvm_datastore_contract.py against the live server):
 *
 * <pre>
 *   GET    /api/version
 *   GET    /api/schemas
 *   GET    /api/schemas/{name}
 *   POST   /api/schemas                       {"name","spec"}
 *   PATCH  /api/schemas/{name}                {"add_spec"}
 *   DELETE /api/schemas/{name}
 *   POST   /api/schemas/{name}/indices        {"attribute"}
 *   DELETE /api/schemas/{name}/indices/{attr}
 *   GET    /api/schemas/{name}/count?cql=
 *   GET    /api/schemas/{name}/bounds
 *   GET    /api/schemas/{name}/features?cql=&max=
 *   POST   /api/schemas/{name}/features       GeoJSON FeatureCollection
 *   DELETE /api/schemas/{name}/features?cql=
 * </pre>
 *
 * The Arrow Flight sidecar (docs/PROTOCOL.md, jvm/GeoMesaTpuFlightClient
 * .java) is the high-throughput alternative; this client trades Arrow
 * columnar streams for zero third-party dependencies, which is what lets
 * the DataStore module compile and smoke-test against nothing but a JDK.
 */
final class TpuRestClient {
    private final String base;
    private final String auths;
    private final HttpClient http;

    TpuRestClient(String baseUrl) {
        this(baseUrl, null);
    }

    TpuRestClient(String baseUrl, String auths) {
        this.base = baseUrl.endsWith("/")
                ? baseUrl.substring(0, baseUrl.length() - 1) : baseUrl;
        this.auths = auths;
        this.http = HttpClient.newBuilder()
                .connectTimeout(Duration.ofSeconds(10))
                .build();
    }

    String baseUrl() { return base; }

    private static String enc(String v) {
        return URLEncoder.encode(v, StandardCharsets.UTF_8);
    }

    private String send(String method, String path, String body)
            throws IOException {
        HttpRequest.Builder rb = HttpRequest.newBuilder()
                .uri(URI.create(base + path))
                .timeout(Duration.ofSeconds(120));
        if (auths != null && !auths.isEmpty()) {
            // visibility authorizations ride every request (the server
            // enforces them on reads AND delete-by-filter)
            rb.header("X-Geomesa-Auths", auths);
        }
        if (body == null) {
            rb.method(method, HttpRequest.BodyPublishers.noBody());
        } else {
            rb.header("Content-Type", "application/json")
              .method(method, HttpRequest.BodyPublishers.ofString(body));
        }
        HttpResponse<String> resp;
        try {
            resp = http.send(rb.build(), HttpResponse.BodyHandlers.ofString());
        } catch (InterruptedException e) {
            Thread.currentThread().interrupt();
            throw new IOException("interrupted talking to " + base, e);
        }
        if (resp.statusCode() >= 400) {
            String msg = resp.body();
            try {
                Object err = MiniJson.parseObject(msg).get("error");
                if (err != null) msg = String.valueOf(err);
            } catch (RuntimeException ignored) {
                // not JSON; keep raw body
            }
            throw new IOException(
                    method + " " + path + " -> HTTP " + resp.statusCode()
                    + ": " + msg);
        }
        return resp.body();
    }

    String version() throws IOException {
        return (String) MiniJson.parseObject(
                send("GET", "/api/version", null)).get("version");
    }

    @SuppressWarnings("unchecked")
    List<Object> listSchemas() throws IOException {
        return (List<Object>) MiniJson.parse(
                send("GET", "/api/schemas", null));
    }

    /** {"name","spec","count","indices"} or IOException(404). */
    Map<String, Object> describeSchema(String name) throws IOException {
        return MiniJson.parseObject(
                send("GET", "/api/schemas/" + enc(name), null));
    }

    void createSchema(String name, String spec) throws IOException {
        send("POST", "/api/schemas", MiniJson.write(
                Map.of("name", name, "spec", spec)));
    }

    void deleteSchema(String name) throws IOException {
        send("DELETE", "/api/schemas/" + enc(name), null);
    }

    /** Append-only schema update: returns the new spec string. */
    String updateSchema(String name, String addSpec) throws IOException {
        return (String) MiniJson.parseObject(send(
                "PATCH", "/api/schemas/" + enc(name),
                MiniJson.write(Map.of("add_spec", addSpec)))).get("spec");
    }

    void addAttributeIndex(String name, String attribute)
            throws IOException {
        send("POST", "/api/schemas/" + enc(name) + "/indices",
                MiniJson.write(Map.of("attribute", attribute)));
    }

    void removeAttributeIndex(String name, String attribute)
            throws IOException {
        send("DELETE", "/api/schemas/" + enc(name) + "/indices/"
                + enc(attribute), null);
    }

    long count(String name, String cql) throws IOException {
        String path = "/api/schemas/" + enc(name) + "/count?cql=" + enc(cql);
        Object n = MiniJson.parseObject(send("GET", path, null)).get("count");
        return ((Number) n).longValue();
    }

    /** [xmin, ymin, xmax, ymax], or null for an empty store. */
    @SuppressWarnings("unchecked")
    List<Object> bounds(String name) throws IOException {
        Object v = MiniJson.parse(
                send("GET", "/api/schemas/" + enc(name) + "/bounds", null));
        return (List<Object>) v;
    }

    /** GeoJSON FeatureCollection for the query. */
    Map<String, Object> features(String name, String cql, int max)
            throws IOException {
        StringBuilder path = new StringBuilder(
                "/api/schemas/" + enc(name) + "/features?cql=" + enc(cql));
        if (max > 0 && max != Integer.MAX_VALUE) {
            path.append("&max=").append(max);
        }
        return MiniJson.parseObject(send("GET", path.toString(), null));
    }

    /** Ingest a GeoJSON FeatureCollection; returns the inserted count. */
    long insertFeatures(String name, Map<String, Object> featureCollection)
            throws IOException {
        String body = MiniJson.write(featureCollection);
        Object n = MiniJson.parseObject(send(
                "POST", "/api/schemas/" + enc(name) + "/features", body)
        ).get("inserted");
        return ((Number) n).longValue();
    }

    long deleteFeatures(String name, String cql) throws IOException {
        Object n = MiniJson.parseObject(send(
                "DELETE",
                "/api/schemas/" + enc(name) + "/features?cql=" + enc(cql),
                null)).get("deleted");
        return ((Number) n).longValue();
    }
}
