package org.locationtech.geomesa.tpu.geotools;

import java.io.IOException;
import java.util.Map;
import org.geotools.api.data.DataStore;
import org.geotools.api.data.DataStoreFactorySpi;

/**
 * GeoTools {@code DataStoreFactorySpi} for geomesa-tpu — the SPI entry
 * point GeoServer/WFS/WMS discover via
 * {@code META-INF/services/org.geotools.api.data.DataStoreFactorySpi}
 * (reference registration: geomesa-accumulo-datastore/src/main/
 * resources/META-INF/services/org.geotools.data.DataStoreFactorySpi;
 * factory shape: geomesa-accumulo-datastore/.../AccumuloDataStoreFactory
 * .scala).
 *
 * <p>Connection parameters:</p>
 * <ul>
 *   <li>{@code geomesa.tpu.rest.url} (required) — base URL of a
 *       geomesa-tpu REST server ({@code geomesa-tpu web} or
 *       {@code geomesa_tpu.web.serve}), e.g.
 *       {@code http://tpu-host:8080}</li>
 *   <li>{@code geomesa.tpu.auths} (optional) — comma-separated
 *       visibility authorizations for queries</li>
 * </ul>
 */
public class GeoMesaTpuDataStoreFactory implements DataStoreFactorySpi {

    /** Base URL of the geomesa-tpu REST server. */
    public static final Param REST_URL_PARAM = new Param(
            "geomesa.tpu.rest.url", String.class,
            "Base URL of the geomesa-tpu REST server", true,
            "http://localhost:8080");

    /** Comma-separated visibility authorizations. */
    public static final Param AUTHS_PARAM = new Param(
            "geomesa.tpu.auths", String.class,
            "Comma-separated visibility authorizations", false);

    @Override public String getDisplayName() {
        return "GeoMesa TPU";
    }

    @Override public String getDescription() {
        return "TPU-native GeoMesa-equivalent feature store "
                + "(JAX/XLA planner and kernels behind a REST/Flight "
                + "sidecar)";
    }

    @Override public Param[] getParametersInfo() {
        return new Param[] { REST_URL_PARAM, AUTHS_PARAM };
    }

    @Override public boolean canProcess(Map<String, ?> params) {
        return params != null && params.get(REST_URL_PARAM.key) != null;
    }

    @Override public boolean isAvailable() {
        return true; // JDK-only transport: no optional dependencies
    }

    @Override public DataStore createDataStore(Map<String, ?> params)
            throws IOException {
        Object url = REST_URL_PARAM.lookUp(params);
        Object auths = AUTHS_PARAM.lookUp(params);
        return new GeoMesaTpuDataStore(
                String.valueOf(url),
                auths == null ? null : String.valueOf(auths));
    }

    @Override public DataStore createNewDataStore(Map<String, ?> params)
            throws IOException {
        // like the reference's factories: the catalog is created lazily
        // on first createSchema, so "new" and "existing" converge
        return createDataStore(params);
    }
}
