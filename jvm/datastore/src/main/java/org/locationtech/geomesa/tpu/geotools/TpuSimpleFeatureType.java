package org.locationtech.geomesa.tpu.geotools;

import java.util.ArrayList;
import java.util.Collections;
import java.util.Date;
import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;
import org.geotools.api.feature.simple.SimpleFeatureType;
import org.geotools.api.feature.type.Name;

/**
 * SimpleFeatureType over a GeoMesa spec string
 * ({@code name:Type[:opt=val],*geom:Point;userdata}) — the same format
 * the reference's SimpleFeatureTypes.createType accepts
 * (geomesa-utils/.../geotools/SimpleFeatureTypes.scala), so specs and
 * tutorials carry over verbatim.
 */
final class TpuSimpleFeatureType implements SimpleFeatureType {

    static final class TpuName implements Name {
        private final String local;
        TpuName(String local) { this.local = local; }
        @Override public String getLocalPart() { return local; }
        @Override public String getNamespaceURI() { return null; }
        @Override public String getURI() { return local; }
        @Override public String toString() { return local; }
    }

    private final String typeName;
    private final String spec;
    private final Map<String, Class<?>> attrs = new LinkedHashMap<>();
    private String geomAttribute;

    TpuSimpleFeatureType(String typeName, String spec) {
        this.typeName = typeName;
        this.spec = spec;
        String attrPart = spec.split(";", 2)[0];
        for (String field : attrPart.split(",")) {
            if (field.isBlank()) continue;
            String f = field.trim();
            boolean isDefaultGeom = f.startsWith("*");
            if (isDefaultGeom) f = f.substring(1);
            String[] bits = f.split(":");
            String name = bits[0];
            String type = bits.length > 1 ? bits[1] : "String";
            Class<?> binding = binding(type);
            attrs.put(name, binding);
            if (isDefaultGeom || (geomAttribute == null
                    && isGeometryType(type))) {
                geomAttribute = name;
            }
        }
    }

    private static boolean isGeometryType(String t) {
        switch (t.toLowerCase()) {
            case "point": case "linestring": case "polygon":
            case "multipoint": case "multilinestring": case "multipolygon":
            case "geometry": case "geometrycollection":
                return true;
            default:
                return false;
        }
    }

    private static Class<?> binding(String t) {
        switch (t.toLowerCase()) {
            case "integer": case "int": return Integer.class;
            case "long": return Long.class;
            case "float": return Float.class;
            case "double": return Double.class;
            case "boolean": return Boolean.class;
            case "date": case "timestamp": return Date.class;
            default:
                // strings, uuids, json, and geometries (carried as
                // GeoJSON-derived maps / WKT strings in this transport)
                return isGeometryType(t) ? Object.class : String.class;
        }
    }

    String getSpec() { return spec; }

    @Override public String getTypeName() { return typeName; }

    @Override public Name getName() { return new TpuName(typeName); }

    @Override public int getAttributeCount() { return attrs.size(); }

    @Override public List<String> getAttributeNames() {
        return Collections.unmodifiableList(new ArrayList<>(attrs.keySet()));
    }

    @Override public Class<?> getType(String name) { return attrs.get(name); }

    @Override public String getGeometryAttribute() { return geomAttribute; }

    @Override public String toString() {
        return "SimpleFeatureType(" + typeName + ", " + spec + ")";
    }
}
