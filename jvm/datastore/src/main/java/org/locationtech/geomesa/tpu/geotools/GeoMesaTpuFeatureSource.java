package org.locationtech.geomesa.tpu.geotools;

import java.io.IOException;
import java.util.List;
import org.geotools.api.data.DataStore;
import org.geotools.api.data.FeatureReader;
import org.geotools.api.data.Query;
import org.geotools.api.data.SimpleFeatureSource;
import org.geotools.api.feature.simple.SimpleFeature;
import org.geotools.api.feature.simple.SimpleFeatureType;
import org.geotools.filter.text.ecql.ECQL;
import org.geotools.geometry.jts.ReferencedEnvelope;

/**
 * SimpleFeatureSource whose bounds/count come from the server's stats
 * subsystem (write-time sketches; the analog of the reference's
 * GeoMesaFeatureSource delegating to stats,
 * geomesa-index-api/.../geotools/GeoMesaFeatureSource.scala) rather
 * than a scan.
 */
final class GeoMesaTpuFeatureSource implements SimpleFeatureSource {

    private final GeoMesaTpuDataStore store;
    private final TpuRestClient client;
    private final TpuSimpleFeatureType type;

    GeoMesaTpuFeatureSource(GeoMesaTpuDataStore store, TpuRestClient client,
                            TpuSimpleFeatureType type) {
        this.store = store;
        this.client = client;
        this.type = type;
    }

    @Override public SimpleFeatureType getSchema() { return type; }

    @Override public DataStore getDataStore() { return store; }

    @Override public ReferencedEnvelope getBounds() throws IOException {
        List<Object> b = client.bounds(type.getTypeName());
        if (b == null || b.size() != 4) {
            return null; // empty store: no bounds yet
        }
        return new ReferencedEnvelope(
                ((Number) b.get(0)).doubleValue(),
                ((Number) b.get(2)).doubleValue(),
                ((Number) b.get(1)).doubleValue(),
                ((Number) b.get(3)).doubleValue());
    }

    @Override public ReferencedEnvelope getBounds(Query query)
            throws IOException {
        // full-extent bounds for filtered queries would need a scan;
        // like the reference, fall back to the schema-wide envelope
        return getBounds();
    }

    @Override public int getCount(Query query) throws IOException {
        String cql = ECQL.toCQL(query == null ? null : query.getFilter());
        return (int) client.count(type.getTypeName(), cql);
    }

    @Override
    public FeatureReader<SimpleFeatureType, SimpleFeature> getFeatures(
            Query query) throws IOException {
        String cql = ECQL.toCQL(query == null ? null : query.getFilter());
        int max = query == null ? Integer.MAX_VALUE : query.getMaxFeatures();
        return new GeoMesaTpuFeatureReader(
                type, client.features(type.getTypeName(), cql, max));
    }
}
