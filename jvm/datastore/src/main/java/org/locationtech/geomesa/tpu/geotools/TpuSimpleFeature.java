package org.locationtech.geomesa.tpu.geotools;

import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;
import org.geotools.api.feature.simple.SimpleFeature;
import org.geotools.api.feature.simple.SimpleFeatureType;

/**
 * SimpleFeature over one GeoJSON feature from the REST transport.
 * Geometry attributes surface as the parsed GeoJSON geometry map
 * ({@code {"type": "Point", "coordinates": [...]}}); scalar attributes
 * as String/Number/Boolean per the schema binding.
 */
final class TpuSimpleFeature implements SimpleFeature {
    private final TpuSimpleFeatureType type;
    private final String id;
    private final Map<String, Object> values = new LinkedHashMap<>();
    private final Object geometry;

    TpuSimpleFeature(TpuSimpleFeatureType type, String id,
                     Object geometry, Map<String, Object> properties) {
        this.type = type;
        this.id = id;
        this.geometry = geometry;
        for (String name : type.getAttributeNames()) {
            if (name.equals(type.getGeometryAttribute())) {
                values.put(name, geometry);
            } else {
                values.put(name, coerce(type.getType(name),
                        properties.get(name)));
            }
        }
    }

    private static Object coerce(Class<?> binding, Object v) {
        if (v == null || binding == null) return v;
        if (binding == Integer.class && v instanceof Number) {
            return ((Number) v).intValue();
        }
        if (binding == Long.class && v instanceof Number) {
            return ((Number) v).longValue();
        }
        if (binding == Float.class && v instanceof Number) {
            return ((Number) v).floatValue();
        }
        if (binding == Double.class && v instanceof Number) {
            return ((Number) v).doubleValue();
        }
        return v;
    }

    @Override public String getID() { return id; }

    @Override public SimpleFeatureType getFeatureType() { return type; }

    @Override public Object getAttribute(String name) {
        return values.get(name);
    }

    @Override public Object getAttribute(int index) {
        List<String> names = type.getAttributeNames();
        return values.get(names.get(index));
    }

    @Override public void setAttribute(String name, Object value) {
        values.put(name, value);
    }

    @Override public Object getDefaultGeometry() { return geometry; }

    Map<String, Object> attributeMap() { return values; }

    @Override public String toString() {
        return "SimpleFeature(" + id + ", " + values + ")";
    }
}
