package org.locationtech.geomesa.tpu.geotools;

import java.io.IOException;
import java.util.ArrayList;
import java.util.List;
import java.util.Map;
import java.util.concurrent.ConcurrentHashMap;
import org.geotools.api.data.DataStore;
import org.geotools.api.data.FeatureReader;
import org.geotools.api.data.FeatureSource;
import org.geotools.api.data.FeatureWriter;
import org.geotools.api.data.LockingManager;
import org.geotools.api.data.Query;
import org.geotools.api.data.ServiceInfo;
import org.geotools.api.data.SimpleFeatureSource;
import org.geotools.api.data.Transaction;
import org.geotools.api.feature.simple.SimpleFeature;
import org.geotools.api.feature.simple.SimpleFeatureType;
import org.geotools.api.feature.type.Name;
import org.geotools.api.filter.Filter;
import org.geotools.filter.text.ecql.ECQL;

/**
 * GeoTools {@code DataStore} over a geomesa-tpu server — the analog of
 * the reference's GeoMesaDataStore
 * (geomesa-index-api/src/main/scala/org/locationtech/geomesa/index/
 * geotools/GeoMesaDataStore.scala:49): schema CRUD against the remote
 * catalog, query planning/execution delegated to the TPU-side planner,
 * results streamed back as features.
 *
 * <p>Transport: the zero-dependency REST client ({@link TpuRestClient});
 * the Arrow Flight client (jvm/GeoMesaTpuFlightClient.java) implements
 * the same method-to-RPC delegation map (docs/PROTOCOL.md §8) for
 * columnar streaming when Arrow jars are on the classpath.</p>
 */
public class GeoMesaTpuDataStore implements DataStore {

    private final TpuRestClient client;
    private final Map<String, TpuSimpleFeatureType> schemaCache =
            new ConcurrentHashMap<>();
    private volatile boolean disposed;

    GeoMesaTpuDataStore(String restUrl) {
        this.client = new TpuRestClient(restUrl);
    }

    private void checkOpen() throws IOException {
        if (disposed) {
            throw new IOException("DataStore has been disposed");
        }
    }

    // -- schema CRUD ------------------------------------------------------

    @Override public void createSchema(SimpleFeatureType featureType)
            throws IOException {
        checkOpen();
        String spec = featureType instanceof TpuSimpleFeatureType
                ? ((TpuSimpleFeatureType) featureType).getSpec()
                : specOf(featureType);
        client.createSchema(featureType.getTypeName(), spec);
        schemaCache.remove(featureType.getTypeName());
    }

    /** Build a spec string from any SimpleFeatureType implementation. */
    private static String specOf(SimpleFeatureType ft) {
        StringBuilder spec = new StringBuilder();
        for (String name : ft.getAttributeNames()) {
            if (spec.length() > 0) spec.append(',');
            Class<?> b = ft.getType(name);
            String t;
            if (name.equals(ft.getGeometryAttribute())) {
                spec.append('*');
                t = "Point";
            } else if (b == Integer.class) {
                t = "Integer";
            } else if (b == Long.class) {
                t = "Long";
            } else if (b == Float.class) {
                t = "Float";
            } else if (b == Double.class) {
                t = "Double";
            } else if (b == Boolean.class) {
                t = "Boolean";
            } else if (b == java.util.Date.class) {
                t = "Date";
            } else {
                t = "String";
            }
            spec.append(name).append(':').append(t);
        }
        return spec.toString();
    }

    @Override public SimpleFeatureType getSchema(String typeName)
            throws IOException {
        checkOpen();
        TpuSimpleFeatureType cached = schemaCache.get(typeName);
        if (cached != null) return cached;
        Map<String, Object> d = client.describeSchema(typeName);
        TpuSimpleFeatureType ft = new TpuSimpleFeatureType(
                typeName, String.valueOf(d.get("spec")));
        schemaCache.put(typeName, ft);
        return ft;
    }

    @Override public SimpleFeatureType getSchema(Name name)
            throws IOException {
        return getSchema(name.getLocalPart());
    }

    @Override public void updateSchema(String typeName,
                                       SimpleFeatureType featureType)
            throws IOException {
        checkOpen();
        // the server's update path is append-only attribute addition
        // (GeoMesaDataStore.scala:288-336 validates transitions the same
        // way); surfaced via the CLI/py API — not this transport yet
        throw new UnsupportedOperationException(
                "updateSchema over REST is not supported yet; use the "
                + "geomesa-tpu CLI (update-schema)");
    }

    @Override public void updateSchema(Name typeName,
                                       SimpleFeatureType featureType)
            throws IOException {
        updateSchema(typeName.getLocalPart(), featureType);
    }

    @Override public void removeSchema(String typeName) throws IOException {
        checkOpen();
        client.deleteSchema(typeName);
        schemaCache.remove(typeName);
    }

    @Override public void removeSchema(Name typeName) throws IOException {
        removeSchema(typeName.getLocalPart());
    }

    @Override public String[] getTypeNames() throws IOException {
        checkOpen();
        List<Object> names = client.listSchemas();
        String[] out = new String[names.size()];
        for (int i = 0; i < out.length; i++) {
            out[i] = String.valueOf(names.get(i));
        }
        return out;
    }

    @Override public List<Name> getNames() throws IOException {
        List<Name> names = new ArrayList<>();
        for (String n : getTypeNames()) {
            names.add(new TpuSimpleFeatureType.TpuName(n));
        }
        return names;
    }

    // -- query / write ----------------------------------------------------

    @Override public SimpleFeatureSource getFeatureSource(String typeName)
            throws IOException {
        return new GeoMesaTpuFeatureSource(
                this, client, (TpuSimpleFeatureType) getSchema(typeName));
    }

    @Override
    public FeatureSource<SimpleFeatureType, SimpleFeature> getFeatureSource(
            Name typeName) throws IOException {
        return getFeatureSource(typeName.getLocalPart());
    }

    @Override
    public FeatureReader<SimpleFeatureType, SimpleFeature> getFeatureReader(
            Query query, Transaction transaction) throws IOException {
        checkOpen();
        TpuSimpleFeatureType ft =
                (TpuSimpleFeatureType) getSchema(query.getTypeName());
        String cql = ECQL.toCQL(query.getFilter());
        return new GeoMesaTpuFeatureReader(ft, client.features(
                ft.getTypeName(), cql, query.getMaxFeatures()));
    }

    @Override
    public FeatureWriter<SimpleFeatureType, SimpleFeature> getFeatureWriter(
            String typeName, Filter filter, Transaction transaction)
            throws IOException {
        // modify-in-place writers need per-feature update RPCs; the
        // supported mutation surface is append + delete-by-filter
        throw new UnsupportedOperationException(
                "modify writers are not supported; use "
                + "getFeatureWriterAppend + deleteFeatures(cql)");
    }

    @Override
    public FeatureWriter<SimpleFeatureType, SimpleFeature> getFeatureWriter(
            String typeName, Transaction transaction) throws IOException {
        return getFeatureWriter(typeName, Filter.INCLUDE, transaction);
    }

    @Override
    public FeatureWriter<SimpleFeatureType, SimpleFeature>
            getFeatureWriterAppend(String typeName, Transaction transaction)
            throws IOException {
        checkOpen();
        return new GeoMesaTpuFeatureWriter(
                client, (TpuSimpleFeatureType) getSchema(typeName));
    }

    /** Delete features matching an ECQL filter (the reference's
     * removeFeatures fast path on GeoMesaFeatureStore). */
    public long deleteFeatures(String typeName, String ecql)
            throws IOException {
        checkOpen();
        return client.deleteFeatures(typeName, ecql);
    }

    // -- infrastructure ---------------------------------------------------

    @Override public ServiceInfo getInfo() {
        return new ServiceInfo() {
            @Override public String getTitle() {
                return "geomesa-tpu @ " + client.baseUrl();
            }
            @Override public String getDescription() {
                return "TPU-native GeoMesa-equivalent feature store "
                        + "(REST transport)";
            }
        };
    }

    @Override public LockingManager getLockingManager() {
        return null; // like the reference: no cross-client locking
    }

    @Override public void dispose() {
        disposed = true;
        schemaCache.clear();
    }
}
