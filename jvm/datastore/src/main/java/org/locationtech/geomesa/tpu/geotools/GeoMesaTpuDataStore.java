package org.locationtech.geomesa.tpu.geotools;

import java.io.IOException;
import java.util.ArrayList;
import java.util.List;
import java.util.Map;
import java.util.concurrent.ConcurrentHashMap;
import org.geotools.api.data.DataStore;
import org.geotools.api.data.FeatureReader;
import org.geotools.api.data.FeatureSource;
import org.geotools.api.data.FeatureWriter;
import org.geotools.api.data.LockingManager;
import org.geotools.api.data.Query;
import org.geotools.api.data.ServiceInfo;
import org.geotools.api.data.SimpleFeatureSource;
import org.geotools.api.data.Transaction;
import org.geotools.api.feature.simple.SimpleFeature;
import org.geotools.api.feature.simple.SimpleFeatureType;
import org.geotools.api.feature.type.Name;
import org.geotools.api.filter.Filter;
import org.geotools.filter.text.ecql.ECQL;

/**
 * GeoTools {@code DataStore} over a geomesa-tpu server — the analog of
 * the reference's GeoMesaDataStore
 * (geomesa-index-api/src/main/scala/org/locationtech/geomesa/index/
 * geotools/GeoMesaDataStore.scala:49): schema CRUD against the remote
 * catalog, query planning/execution delegated to the TPU-side planner,
 * results streamed back as features.
 *
 * <p>Transport: the zero-dependency REST client ({@link TpuRestClient});
 * the Arrow Flight client (jvm/GeoMesaTpuFlightClient.java) implements
 * the same method-to-RPC delegation map (docs/PROTOCOL.md §8) for
 * columnar streaming when Arrow jars are on the classpath.</p>
 */
public class GeoMesaTpuDataStore implements DataStore {

    private final TpuRestClient client;
    private final Map<String, TpuSimpleFeatureType> schemaCache =
            new ConcurrentHashMap<>();
    private volatile boolean disposed;

    GeoMesaTpuDataStore(String restUrl) {
        this(restUrl, null);
    }

    GeoMesaTpuDataStore(String restUrl, String auths) {
        this.client = new TpuRestClient(restUrl, auths);
    }

    private void checkOpen() throws IOException {
        if (disposed) {
            throw new IOException("DataStore has been disposed");
        }
    }

    // -- schema CRUD ------------------------------------------------------

    @Override public void createSchema(SimpleFeatureType featureType)
            throws IOException {
        checkOpen();
        String spec = featureType instanceof TpuSimpleFeatureType
                ? ((TpuSimpleFeatureType) featureType).getSpec()
                : specOf(featureType);
        client.createSchema(featureType.getTypeName(), spec);
        schemaCache.remove(featureType.getTypeName());
    }

    /** Java class -> spec type name (shared by create/update paths). */
    private static String specType(Class<?> b) {
        if (b == Integer.class) return "Integer";
        if (b == Long.class) return "Long";
        if (b == Float.class) return "Float";
        if (b == Double.class) return "Double";
        if (b == Boolean.class) return "Boolean";
        if (b == java.util.Date.class) return "Date";
        return "String";
    }

    /** Build a spec string from any SimpleFeatureType implementation. */
    private static String specOf(SimpleFeatureType ft) {
        StringBuilder spec = new StringBuilder();
        for (String name : ft.getAttributeNames()) {
            if (spec.length() > 0) spec.append(',');
            if (name.equals(ft.getGeometryAttribute())) {
                spec.append('*').append(name).append(":Point");
            } else {
                spec.append(name).append(':')
                    .append(specType(ft.getType(name)));
            }
        }
        return spec.toString();
    }

    @Override public SimpleFeatureType getSchema(String typeName)
            throws IOException {
        checkOpen();
        TpuSimpleFeatureType cached = schemaCache.get(typeName);
        if (cached != null) return cached;
        Map<String, Object> d = client.describeSchema(typeName);
        TpuSimpleFeatureType ft = new TpuSimpleFeatureType(
                typeName, String.valueOf(d.get("spec")));
        schemaCache.put(typeName, ft);
        return ft;
    }

    @Override public SimpleFeatureType getSchema(Name name)
            throws IOException {
        return getSchema(name.getLocalPart());
    }

    @Override public void updateSchema(String typeName,
                                       SimpleFeatureType featureType)
            throws IOException {
        checkOpen();
        // append-only attribute addition — the ONLY transition the
        // reference's updateSchema permits (GeoMesaDataStore.scala:
        // 288-336 validates and rejects everything else). Removed or
        // retyped attributes are rejected loudly rather than silently
        // ignored; server-side the append is in place (no row re-flush).
        SimpleFeatureType current = getSchema(typeName);
        for (String name : current.getAttributeNames()) {
            if (!featureType.getAttributeNames().contains(name)) {
                throw new UnsupportedOperationException(
                        "updateSchema is append-only: cannot remove "
                        + "attribute " + name);
            }
            if (name.equals(current.getGeometryAttribute())) {
                continue; // geometry bindings are opaque in this client
            }
            if (!specType(current.getType(name)).equals(
                    specType(featureType.getType(name)))) {
                throw new UnsupportedOperationException(
                        "updateSchema is append-only: cannot change the "
                        + "type of attribute " + name);
            }
        }
        StringBuilder add = new StringBuilder();
        for (String name : featureType.getAttributeNames()) {
            if (current.getAttributeNames().contains(name)) {
                continue;
            }
            Class<?> b = featureType.getType(name);
            if (name.equals(featureType.getGeometryAttribute())
                    || b == Object.class) {
                // Object.class is this client's binding for every
                // geometry type — adding geometries is not supported
                throw new UnsupportedOperationException(
                        "cannot add geometry attributes to a schema");
            }
            if (add.length() > 0) add.append(',');
            add.append(name).append(':').append(specType(b));
        }
        if (add.length() > 0) {
            client.updateSchema(typeName, add.toString());
        }
        schemaCache.remove(typeName);
    }

    @Override public void updateSchema(Name typeName,
                                       SimpleFeatureType featureType)
            throws IOException {
        updateSchema(typeName.getLocalPart(), featureType);
    }

    @Override public void removeSchema(String typeName) throws IOException {
        checkOpen();
        client.deleteSchema(typeName);
        schemaCache.remove(typeName);
    }

    @Override public void removeSchema(Name typeName) throws IOException {
        removeSchema(typeName.getLocalPart());
    }

    @Override public String[] getTypeNames() throws IOException {
        checkOpen();
        List<Object> names = client.listSchemas();
        String[] out = new String[names.size()];
        for (int i = 0; i < out.length; i++) {
            out[i] = String.valueOf(names.get(i));
        }
        return out;
    }

    @Override public List<Name> getNames() throws IOException {
        List<Name> names = new ArrayList<>();
        for (String n : getTypeNames()) {
            names.add(new TpuSimpleFeatureType.TpuName(n));
        }
        return names;
    }

    // -- query / write ----------------------------------------------------

    @Override public SimpleFeatureSource getFeatureSource(String typeName)
            throws IOException {
        return new GeoMesaTpuFeatureSource(
                this, client, (TpuSimpleFeatureType) getSchema(typeName));
    }

    @Override
    public FeatureSource<SimpleFeatureType, SimpleFeature> getFeatureSource(
            Name typeName) throws IOException {
        return getFeatureSource(typeName.getLocalPart());
    }

    @Override
    public FeatureReader<SimpleFeatureType, SimpleFeature> getFeatureReader(
            Query query, Transaction transaction) throws IOException {
        checkOpen();
        TpuSimpleFeatureType ft =
                (TpuSimpleFeatureType) getSchema(query.getTypeName());
        String cql = ECQL.toCQL(query.getFilter());
        return new GeoMesaTpuFeatureReader(ft, client.features(
                ft.getTypeName(), cql, query.getMaxFeatures()));
    }

    @Override
    public FeatureWriter<SimpleFeatureType, SimpleFeature> getFeatureWriter(
            String typeName, Filter filter, Transaction transaction)
            throws IOException {
        // modify-in-place writers need per-feature update RPCs; the
        // supported mutation surface is append + delete-by-filter
        throw new UnsupportedOperationException(
                "modify writers are not supported; use "
                + "getFeatureWriterAppend + deleteFeatures(cql)");
    }

    @Override
    public FeatureWriter<SimpleFeatureType, SimpleFeature> getFeatureWriter(
            String typeName, Transaction transaction) throws IOException {
        return getFeatureWriter(typeName, Filter.INCLUDE, transaction);
    }

    @Override
    public FeatureWriter<SimpleFeatureType, SimpleFeature>
            getFeatureWriterAppend(String typeName, Transaction transaction)
            throws IOException {
        checkOpen();
        return new GeoMesaTpuFeatureWriter(
                client, (TpuSimpleFeatureType) getSchema(typeName));
    }

    /** Delete features matching an ECQL filter (the reference's
     * removeFeatures fast path on GeoMesaFeatureStore). */
    public long deleteFeatures(String typeName, String ecql)
            throws IOException {
        checkOpen();
        return client.deleteFeatures(typeName, ecql);
    }

    /** Enable an attribute index on a live schema (no store recreate;
     * the server builds only the new permutation). */
    public void addAttributeIndex(String typeName, String attribute)
            throws IOException {
        checkOpen();
        client.addAttributeIndex(typeName, attribute);
        schemaCache.remove(typeName);
    }

    /** Drop an attribute index; data is untouched. */
    public void removeAttributeIndex(String typeName, String attribute)
            throws IOException {
        checkOpen();
        client.removeAttributeIndex(typeName, attribute);
        schemaCache.remove(typeName);
    }

    // -- infrastructure ---------------------------------------------------

    @Override public ServiceInfo getInfo() {
        return new ServiceInfo() {
            @Override public String getTitle() {
                return "geomesa-tpu @ " + client.baseUrl();
            }
            @Override public String getDescription() {
                return "TPU-native GeoMesa-equivalent feature store "
                        + "(REST transport)";
            }
        };
    }

    @Override public LockingManager getLockingManager() {
        return null; // like the reference: no cross-client locking
    }

    @Override public void dispose() {
        disposed = true;
        schemaCache.clear();
    }
}
