package org.locationtech.geomesa.tpu.geotools;

import java.util.ArrayList;
import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;

/**
 * Minimal JSON parser/writer (JDK-only, no third-party dependency) for
 * the REST transport. Parses into Map/List/String/Double/Boolean/null;
 * writes the same shapes back. Sufficient for the geomesa-tpu REST
 * surface (geomesa_tpu/web.py); not a general-purpose JSON library.
 */
final class MiniJson {
    private final String s;
    private int i;

    private MiniJson(String s) { this.s = s; }

    static Object parse(String text) {
        MiniJson p = new MiniJson(text);
        Object v = p.value();
        p.ws();
        if (p.i != p.s.length()) {
            throw new IllegalArgumentException(
                    "trailing JSON at offset " + p.i);
        }
        return v;
    }

    @SuppressWarnings("unchecked")
    static Map<String, Object> parseObject(String text) {
        return (Map<String, Object>) parse(text);
    }

    private void ws() {
        while (i < s.length() && Character.isWhitespace(s.charAt(i))) i++;
    }

    private char peek() {
        if (i >= s.length()) throw new IllegalArgumentException("eof");
        return s.charAt(i);
    }

    private Object value() {
        ws();
        char c = peek();
        switch (c) {
            case '{': return object();
            case '[': return array();
            case '"': return string();
            case 't': expect("true"); return Boolean.TRUE;
            case 'f': expect("false"); return Boolean.FALSE;
            case 'n': expect("null"); return null;
            default: return number();
        }
    }

    private void expect(String lit) {
        if (!s.startsWith(lit, i)) {
            throw new IllegalArgumentException(
                    "bad literal at offset " + i);
        }
        i += lit.length();
    }

    private Map<String, Object> object() {
        Map<String, Object> m = new LinkedHashMap<>();
        i++; // {
        ws();
        if (peek() == '}') { i++; return m; }
        while (true) {
            ws();
            String k = string();
            ws();
            if (peek() != ':') throw new IllegalArgumentException(
                    "expected : at offset " + i);
            i++;
            m.put(k, value());
            ws();
            char c = peek();
            i++;
            if (c == '}') return m;
            if (c != ',') throw new IllegalArgumentException(
                    "expected , or } at offset " + (i - 1));
        }
    }

    private List<Object> array() {
        List<Object> l = new ArrayList<>();
        i++; // [
        ws();
        if (peek() == ']') { i++; return l; }
        while (true) {
            l.add(value());
            ws();
            char c = peek();
            i++;
            if (c == ']') return l;
            if (c != ',') throw new IllegalArgumentException(
                    "expected , or ] at offset " + (i - 1));
        }
    }

    private String string() {
        if (peek() != '"') throw new IllegalArgumentException(
                "expected string at offset " + i);
        i++;
        StringBuilder b = new StringBuilder();
        while (true) {
            char c = s.charAt(i++);
            if (c == '"') return b.toString();
            if (c == '\\') {
                char e = s.charAt(i++);
                switch (e) {
                    case '"': b.append('"'); break;
                    case '\\': b.append('\\'); break;
                    case '/': b.append('/'); break;
                    case 'b': b.append('\b'); break;
                    case 'f': b.append('\f'); break;
                    case 'n': b.append('\n'); break;
                    case 'r': b.append('\r'); break;
                    case 't': b.append('\t'); break;
                    case 'u':
                        b.append((char) Integer.parseInt(
                                s.substring(i, i + 4), 16));
                        i += 4;
                        break;
                    default: throw new IllegalArgumentException(
                            "bad escape \\" + e);
                }
            } else {
                b.append(c);
            }
        }
    }

    private Double number() {
        int start = i;
        while (i < s.length() && "+-0123456789.eE".indexOf(s.charAt(i)) >= 0) {
            i++;
        }
        return Double.parseDouble(s.substring(start, i));
    }

    // -- writer -----------------------------------------------------------

    static String write(Object v) {
        StringBuilder b = new StringBuilder();
        writeTo(b, v);
        return b.toString();
    }

    private static void writeTo(StringBuilder b, Object v) {
        if (v == null) {
            b.append("null");
        } else if (v instanceof String) {
            writeString(b, (String) v);
        } else if (v instanceof Map) {
            b.append('{');
            boolean first = true;
            for (Map.Entry<?, ?> e : ((Map<?, ?>) v).entrySet()) {
                if (!first) b.append(',');
                first = false;
                writeString(b, String.valueOf(e.getKey()));
                b.append(':');
                writeTo(b, e.getValue());
            }
            b.append('}');
        } else if (v instanceof Iterable) {
            b.append('[');
            boolean first = true;
            for (Object o : (Iterable<?>) v) {
                if (!first) b.append(',');
                first = false;
                writeTo(b, o);
            }
            b.append(']');
        } else if (v instanceof Double || v instanceof Float) {
            double d = ((Number) v).doubleValue();
            if (d == Math.floor(d) && !Double.isInfinite(d)
                    && Math.abs(d) < 1e15) {
                b.append((long) d);
            } else {
                b.append(d);
            }
        } else if (v instanceof Number || v instanceof Boolean) {
            b.append(v);
        } else {
            writeString(b, String.valueOf(v));
        }
    }

    private static void writeString(StringBuilder b, String v) {
        b.append('"');
        for (int j = 0; j < v.length(); j++) {
            char c = v.charAt(j);
            switch (c) {
                case '"': b.append("\\\""); break;
                case '\\': b.append("\\\\"); break;
                case '\n': b.append("\\n"); break;
                case '\r': b.append("\\r"); break;
                case '\t': b.append("\\t"); break;
                default:
                    if (c < 0x20) {
                        b.append(String.format("\\u%04x", (int) c));
                    } else {
                        b.append(c);
                    }
            }
        }
        b.append('"');
    }
}
