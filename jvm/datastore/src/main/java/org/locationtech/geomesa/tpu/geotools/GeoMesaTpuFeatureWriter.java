package org.locationtech.geomesa.tpu.geotools;

import java.io.IOException;
import java.util.ArrayList;
import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;
import org.geotools.api.data.FeatureWriter;
import org.geotools.api.feature.simple.SimpleFeature;
import org.geotools.api.feature.simple.SimpleFeatureType;

/**
 * Append-mode FeatureWriter: features accumulate locally and flush as
 * one GeoJSON FeatureCollection POST on {@link #close()} (matching the
 * reference's batched writer flush,
 * geomesa-index-api/.../api/IndexAdapter WriteAdapter semantics — one
 * mutation batch per flush, not one RPC per feature).
 */
final class GeoMesaTpuFeatureWriter
        implements FeatureWriter<SimpleFeatureType, SimpleFeature> {

    private final TpuRestClient client;
    private final TpuSimpleFeatureType type;
    private final List<Object> pending = new ArrayList<>();
    private TpuSimpleFeature current;
    private long counter;

    GeoMesaTpuFeatureWriter(TpuRestClient client, TpuSimpleFeatureType type) {
        this.client = client;
        this.type = type;
    }

    @Override public SimpleFeatureType getFeatureType() { return type; }

    @Override public boolean hasNext() { return false; } // append-only

    @Override public SimpleFeature next() {
        current = new TpuSimpleFeature(
                type, type.getTypeName() + "-" + (counter++),
                null, new LinkedHashMap<>());
        return current;
    }

    @Override public void remove() throws IOException {
        throw new IOException(
                "append-only writer: use deleteFeatures(cql) to remove");
    }

    @Override public void write() throws IOException {
        if (current == null) {
            throw new IOException("call next() before write()");
        }
        Map<String, Object> f = new LinkedHashMap<>();
        f.put("type", "Feature");
        f.put("id", current.getID());
        Object geom = current.getAttribute(type.getGeometryAttribute());
        if (geom == null) {
            throw new IOException("feature " + current.getID()
                    + " has no geometry (attribute "
                    + type.getGeometryAttribute() + ")");
        }
        f.put("geometry", geom);
        Map<String, Object> props = new LinkedHashMap<>();
        for (String name : type.getAttributeNames()) {
            if (!name.equals(type.getGeometryAttribute())) {
                props.put(name, current.getAttribute(name));
            }
        }
        f.put("properties", props);
        pending.add(f);
        current = null;
    }

    @Override public void close() throws IOException {
        if (!pending.isEmpty()) {
            Map<String, Object> fc = new LinkedHashMap<>();
            fc.put("type", "FeatureCollection");
            fc.put("features", pending);
            client.insertFeatures(type.getTypeName(), fc);
            pending.clear();
        }
    }
}
