/*
 * GeoMesaTpuFlightClient — single-file JVM client for the geomesa-tpu
 * sidecar, implementing docs/PROTOCOL.md v1 over Arrow Flight.
 *
 * This is the delegation layer a GeoTools DataStore builds on (the
 * reference surface: GeoMesaDataStore.scala:49; SPI registration via
 * META-INF/services/org.geotools.data.DataStoreFactorySpi). The method ->
 * RPC mapping is PROTOCOL.md §8:
 *
 *   DataStore.createSchema(sft)        -> createSchema(name, specString)
 *   DataStore.getTypeNames()           -> listSchemas()
 *   DataStore.getSchema(name)          -> getSpec(name)  (geomesa:spec
 *                                         metadata -> SimpleFeatureTypes.createType)
 *   DataStore.removeSchema(name)       -> deleteSchema(name)
 *   DataStore.getFeatureReader(q, tx)  -> query(name, ecql, props, max, ...)
 *   FeatureSource.getCount(query)      -> count(name, ecql)
 *   DensityProcess hints               -> density(name, ecql, bbox, w, h)
 *   StatsProcess hints                 -> statsJson(name, statDsl, ecql)
 *   store init version check           -> checkVersion()
 *
 * Dependencies (no GeoTools needed for this file):
 *   org.apache.arrow:flight-core:15+  org.apache.arrow:arrow-memory-netty:15+
 *
 * Build+smoke-test (against `geomesa-tpu serve --catalog /tmp/cat`):
 *   javac -cp "$ARROW_JARS" GeoMesaTpuFlightClient.java
 *   java  -cp "$ARROW_JARS:." GeoMesaTpuFlightClient grpc+tcp://127.0.0.1:8815
 */

import java.nio.charset.StandardCharsets;
import java.util.ArrayList;
import java.util.Iterator;
import java.util.List;

import org.apache.arrow.flight.Action;
import org.apache.arrow.flight.FlightClient;
import org.apache.arrow.flight.FlightDescriptor;
import org.apache.arrow.flight.FlightInfo;
import org.apache.arrow.flight.FlightStream;
import org.apache.arrow.flight.Location;
import org.apache.arrow.flight.Result;
import org.apache.arrow.flight.Ticket;
import org.apache.arrow.memory.BufferAllocator;
import org.apache.arrow.memory.RootAllocator;
import org.apache.arrow.vector.VectorSchemaRoot;

public final class GeoMesaTpuFlightClient implements AutoCloseable {

    /** PROTOCOL.md v1 — refuse servers speaking a different major. */
    public static final int PROTOCOL_VERSION = 1;

    private final BufferAllocator allocator;
    private final FlightClient client;

    public GeoMesaTpuFlightClient(String location) {
        this.allocator = new RootAllocator(Long.MAX_VALUE);
        this.client = FlightClient.builder(
                allocator, new Location(java.net.URI.create(location))).build();
    }

    // -- tiny JSON helpers (flat protocol objects only; no dependency) ----
    private static String jstr(String s) {
        StringBuilder b = new StringBuilder("\"");
        for (int i = 0; i < s.length(); i++) {
            char c = s.charAt(i);
            if (c == '"' || c == '\\') b.append('\\');
            if (c == '\n') { b.append("\\n"); continue; }
            b.append(c);
        }
        return b.append('"').toString();
    }

    /** Extract a string field from a flat JSON object (protocol responses
     *  are flat; a full JSON parser is overkill for the handshake path). */
    static String jget(String json, String key) {
        String needle = "\"" + key + "\"";
        int i = json.indexOf(needle);
        if (i < 0) return null;
        int colon = json.indexOf(':', i + needle.length());
        int j = colon + 1;
        while (j < json.length() && Character.isWhitespace(json.charAt(j))) j++;
        if (json.charAt(j) == '"') {
            int end = json.indexOf('"', j + 1);
            while (end > 0 && json.charAt(end - 1) == '\\') end = json.indexOf('"', end + 1);
            return json.substring(j + 1, end);
        }
        int end = j;
        while (end < json.length() && "-+.0123456789".indexOf(json.charAt(end)) >= 0) end++;
        return json.substring(j, end);
    }

    private String action(String kind, String bodyJson) {
        Iterator<Result> it = client.doAction(
                new Action(kind, bodyJson.getBytes(StandardCharsets.UTF_8)));
        StringBuilder out = new StringBuilder();
        while (it.hasNext()) out.append(new String(it.next().getBody(), StandardCharsets.UTF_8));
        return out.toString();
    }

    // -- PROTOCOL §1: version handshake -----------------------------------
    public void checkVersion() {
        String resp = action("version", "{}");
        int server = Integer.parseInt(jget(resp, "protocol"));
        if (server != PROTOCOL_VERSION) {
            throw new IllegalStateException(
                "sidecar protocol mismatch: server=" + server
                + " client=" + PROTOCOL_VERSION + "; upgrade the older side");
        }
    }

    // -- PROTOCOL §5: schema CRUD / management ----------------------------
    public String createSchema(String name, String spec) {
        return jget(action("create-schema",
                "{\"name\": " + jstr(name) + ", \"spec\": " + jstr(spec) + "}"),
                "created");
    }

    public void deleteSchema(String name) {
        action("delete-schema", "{\"name\": " + jstr(name) + "}");
    }

    public List<String> listSchemas() {
        String resp = action("list-schemas", "{}");
        List<String> out = new ArrayList<>();
        int i = resp.indexOf('[');
        int end = resp.indexOf(']', i);
        for (String part : resp.substring(i + 1, end).split(",")) {
            String t = part.trim();
            if (t.length() > 1) out.add(t.substring(1, t.length() - 1));
        }
        return out;
    }

    /** Spec string for GeoTools SimpleFeatureTypes.createType (PROTOCOL §2:
     *  carried as the geomesa:spec metadata key on every Arrow schema). */
    public String getSpec(String name) {
        FlightInfo info = client.getInfo(FlightDescriptor.path(name));
        byte[] spec = info.getSchema().getCustomMetadata() == null ? null
                : info.getSchema().getCustomMetadata().get("geomesa:spec") == null ? null
                : info.getSchema().getCustomMetadata().get("geomesa:spec")
                      .getBytes(StandardCharsets.UTF_8);
        return spec == null ? null : new String(spec, StandardCharsets.UTF_8);
    }

    public long count(String name, String ecql) {
        String resp = action("count",
                "{\"name\": " + jstr(name) + ", \"ecql\": " + jstr(ecql) + "}");
        return Long.parseLong(jget(resp, "count"));
    }

    public String explain(String name, String ecql) {
        return jget(action("explain",
                "{\"name\": " + jstr(name) + ", \"ecql\": " + jstr(ecql) + "}"),
                "explain");
    }

    // -- PROTOCOL §3: reads ------------------------------------------------
    /** Feature scan: the FeatureReader delegate. Caller iterates the
     *  FlightStream's VectorSchemaRoot batches (arrives incrementally with
     *  dictionary deltas — DeltaWriter semantics) and wraps rows as
     *  SimpleFeatures. */
    public FlightStream query(String name, String ecql, List<String> properties,
                              Long maxFeatures, Integer sampling) {
        StringBuilder t = new StringBuilder("{\"op\": \"query\", \"schema\": ")
                .append(jstr(name)).append(", \"ecql\": ").append(jstr(ecql));
        if (properties != null && !properties.isEmpty()) {
            t.append(", \"properties\": [");
            for (int i = 0; i < properties.size(); i++) {
                if (i > 0) t.append(", ");
                t.append(jstr(properties.get(i)));
            }
            t.append(']');
        }
        if (maxFeatures != null) t.append(", \"max_features\": ").append(maxFeatures);
        if (sampling != null) t.append(", \"sampling\": ").append(sampling);
        t.append('}');
        return client.getStream(new Ticket(t.toString().getBytes(StandardCharsets.UTF_8)));
    }

    /** Density heatmap (DensityProcess delegate): sparse row/col/weight. */
    public FlightStream density(String name, String ecql, double[] bbox,
                                int width, int height) {
        String t = "{\"op\": \"density\", \"schema\": " + jstr(name)
                + ", \"ecql\": " + jstr(ecql)
                + ", \"bbox\": [" + bbox[0] + ", " + bbox[1] + ", " + bbox[2]
                + ", " + bbox[3] + "], \"width\": " + width
                + ", \"height\": " + height + "}";
        return client.getStream(new Ticket(t.getBytes(StandardCharsets.UTF_8)));
    }

    /** Stats sketch (StatsProcess delegate): returns the sketch JSON. */
    public String statsJson(String name, String statDsl, String ecql) {
        String t = "{\"op\": \"stats\", \"schema\": " + jstr(name)
                + ", \"ecql\": " + jstr(ecql) + ", \"stat\": " + jstr(statDsl) + "}";
        try (FlightStream s = client.getStream(
                new Ticket(t.getBytes(StandardCharsets.UTF_8)))) {
            StringBuilder out = new StringBuilder();
            while (s.next()) {
                VectorSchemaRoot root = s.getRoot();
                if (root.getRowCount() > 0) {
                    out.append(root.getVector("value").getObject(0).toString());
                }
            }
            return out.toString();
        } catch (Exception e) {
            throw new RuntimeException(e);
        }
    }

    @Override
    public void close() throws Exception {
        client.close();
        allocator.close();
    }

    // -- smoke test: the conformance lifecycle against a live sidecar -----
    public static void main(String[] args) throws Exception {
        String loc = args.length > 0 ? args[0] : "grpc+tcp://127.0.0.1:8815";
        try (GeoMesaTpuFlightClient c = new GeoMesaTpuFlightClient(loc)) {
            c.checkVersion();
            System.out.println("handshake OK (protocol " + PROTOCOL_VERSION + ")");
            String spec = "name:String:index=true,dtg:Date,*geom:Point";
            c.createSchema("jvm_smoke", spec);
            System.out.println("schemas: " + c.listSchemas());
            System.out.println("spec round-trip: " + spec.equals(c.getSpec("jvm_smoke")));
            System.out.println("count(INCLUDE) = " + c.count("jvm_smoke", "INCLUDE"));
            System.out.println(c.explain("jvm_smoke",
                    "BBOX(geom, -10, -10, 10, 10)"));
            long rows = 0;
            try (FlightStream s = c.query("jvm_smoke", "INCLUDE", null, null, null)) {
                while (s.next()) rows += s.getRoot().getRowCount();
            }
            System.out.println("query rows = " + rows);
            c.deleteSchema("jvm_smoke");
            System.out.println("lifecycle OK");
        }
    }
}
