"""Benchmark: bbox+time CQL filter + density heatmap throughput.

The north-star configuration (BASELINE.md): features/sec on a spatio-temporal
filter + density aggregation, device vs single-threaded-process numpy CPU
baseline (the reference provides no published numbers; the CPU path here IS
the measured baseline, per BASELINE.md).

Prints ONE JSON line. Success: {"metric", "value", "unit", "vs_baseline"}
plus driver-checkable extras (p50_e2e_density_ms, device_ms, cpu_ms, n_rows,
rows_scanned, rows_matched, ingest_s, warm_requery_ms,
recompiles_per_100_queries). When the accelerator probe fails, the bench
FALLS BACK to JAX_PLATFORMS=cpu and reports REAL CPU numbers annotated with
"device_unreachable": true (plus "probe_error" when the probe died with a
non-zero rc) — never a zeroed metric that poisons the trajectory (the
BENCH_r05 failure mode). Only a crash mid-run exits non-zero.

``--smoke``: CI mode — tiny dataset (200k rows), forced CPU backend with a
virtual 8-device mesh (GEOMESA_BENCH_DEVICES), no device probe; same JSON
keys plus "smoke": true, so warm-path regressions
(recompiles_per_100_queries > 0), sharded-scan bit-identity, and
pool-parallelism regressions are caught without TPU access. Multi-device
keys: sharded_scan_speedup, sharded_device_dispatches, pool_qps_scaleup,
pool_slot_dispatches — plus "parallel_headroom_limited": true when the
host's cores cannot express the fan-out (2-core boxes: the speedups are
honest-but-flat; the CI >1.5x gates condition on headroom, the
bit-identity/parallelism gates hold everywhere).

Env knobs: GEOMESA_BENCH_N (points, default 20M; 200k under --smoke),
GEOMESA_BENCH_ITERS, GEOMESA_BENCH_PROBE_{ATTEMPTS,TIMEOUT,BACKOFF},
GEOMESA_BENCH_RESET_CMD, GEOMESA_BENCH_WALL_TIMEOUT (whole-run watchdog
seconds, default 1800, 0 disables — raise it for runs expected to exceed
30 minutes).
"""

import json
import os
import sys
import time

import numpy as np


def _timed(fn) -> float:
    t0 = time.time()
    fn()
    return time.time() - t0


def _probe_device() -> "dict | None":
    """Probe the accelerator with bounded retries. A dead/wedged device
    claim makes ``jax.devices()`` block indefinitely in PJRT init (seen
    with the tunneled TPU after a client was killed mid-compile), which
    would hang this process forever; probing in a THROWAWAY subprocess
    bounds the damage.

    Round-4 lesson: one wedged claim must not zero a round's evidence.
    Round-5 lesson: even a PARSEABLE zeroed metric poisons the
    trajectory — so the caller now falls back to a real CPU run after
    the FIRST failed probe (GEOMESA_BENCH_PROBE_ATTEMPTS default 1;
    raise it to re-probe with the optional GEOMESA_BENCH_RESET_CMD
    operator reset hook between attempts).

    Returns None if the device answered; otherwise a dict of failure keys
    to merge into the emitted JSON line: always "device_unreachable": true,
    plus "probe_error" with the last stderr tail when the probe failed with
    a non-zero rc (a wedged claim can fail fast with "device already in
    use", so non-zero rcs are retried with the reset hook too; the stderr
    in the JSON keeps a genuine install error diagnosable).
    """
    import subprocess

    attempts = int(os.environ.get("GEOMESA_BENCH_PROBE_ATTEMPTS", 1))
    timeout_s = int(os.environ.get("GEOMESA_BENCH_PROBE_TIMEOUT", 240))
    backoff_s = int(os.environ.get("GEOMESA_BENCH_PROBE_BACKOFF", 15))
    reset_cmd = os.environ.get("GEOMESA_BENCH_RESET_CMD")

    last_err = ""
    for attempt in range(1, attempts + 1):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout_s, capture_output=True,
            )
            if proc.returncode == 0:
                return None
            last_err = proc.stderr.decode(errors="replace")[-2000:]
            sys.stderr.write(
                f"device probe {attempt}/{attempts} failed "
                f"(rc={proc.returncode}):\n{last_err}"
            )
        except subprocess.TimeoutExpired:
            sys.stderr.write(
                f"device probe {attempt}/{attempts} hung for {timeout_s}s: "
                "accelerator unreachable (likely a wedged device claim / "
                "dead tunnel).\n"
            )
        if attempt < attempts:
            if reset_cmd:
                sys.stderr.write(f"running reset hook: {reset_cmd}\n")
                try:
                    subprocess.run(reset_cmd, shell=True, timeout=120)
                except Exception as e:
                    sys.stderr.write(f"reset hook failed: {e!r}\n")
            wait = backoff_s * (2 ** (attempt - 1))
            sys.stderr.write(f"backing off {wait}s before re-probe\n")
            time.sleep(wait)
    failure = {"device_unreachable": True}
    if last_err:
        failure["probe_error"] = last_err[-500:]
    return failure


#: Last bench round that measured a REAL accelerator (ROADMAP bench
#: infra): rounds 1-3 ran on device (58-86 G features/s); every round
#: since is CPU fallback or a forced-CPU harness.
_LAST_DEVICE_ROUND = 3


def _device_baseline(fallback_reason=None, probe=True) -> dict:
    """The ``device_baseline`` provenance block merged into EVERY bench
    JSON line: which backend produced the numbers, why it is (or is not)
    a fallback, and the last round with a real-accelerator datapoint —
    so the rounds-4+ CPU-fallback gap is machine-readable instead of a
    footnote the driver has to remember. ``fallback_reason`` is None
    only when the run really measured the accelerator; ``probe=False``
    skips touching jax (the mid-run watchdog must not block on a wedged
    device claim)."""
    platform, n_devices = "unknown", 0
    if probe:
        try:
            import jax

            devs = jax.devices()
            platform = str(devs[0].platform)
            n_devices = len(devs)
        except Exception as e:  # pragma: no cover - broken install
            platform = f"unavailable: {e!r}"[:120]
    block = {
        "platform": platform,
        "n_devices": n_devices,
        "cpu_fallback": bool(fallback_reason) or platform == "cpu",
        "last_device_round": _LAST_DEVICE_ROUND,
    }
    if fallback_reason is not None:
        block["fallback_reason"] = str(fallback_reason)
    elif platform == "cpu":
        block["fallback_reason"] = "cpu-backend"
    return {"device_baseline": block}


def _arm_watchdog() -> None:
    """The probe catches a PRE-wedged device; this catches one that
    wedges MID-run (enqueue acks but execution never completes — the
    bench would hang past the probe and the round would again end with
    no JSON). After GEOMESA_BENCH_WALL_TIMEOUT seconds the watchdog
    prints the failure line and hard-exits."""
    import threading

    wall_s = int(os.environ.get("GEOMESA_BENCH_WALL_TIMEOUT", 1800))
    if wall_s <= 0:
        return

    def fire():
        sys.stderr.write(
            f"bench exceeded the {wall_s}s wall-clock watchdog "
            "(device wedged mid-run?)\n"
        )
        print(json.dumps({
            "metric": "bbox_time_density_scan_throughput",
            "value": 0,
            "unit": "features/sec",
            "vs_baseline": 0,
            "device_unreachable": True,
            "probe_error": f"wall-clock watchdog fired after {wall_s}s",
            **_device_baseline("wall-clock-watchdog", probe=False),
        }), flush=True)
        os._exit(3)

    t = threading.Timer(wall_s, fire)
    t.daemon = True
    t.start()


def _force_cpu(n_devices: int = 0) -> None:
    """Route this process onto the CPU backend (the axon TPU plugin's
    sitecustomize overrides JAX_PLATFORMS at startup, so the jax.config
    update is required too). ``n_devices`` > 1 provisions a virtual
    CPU device mesh (GEOMESA_BENCH_DEVICES; the 8-device CI smoke) so the
    sharded-scan/serving-pool keys exercise the real fan-out paths."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices > 1:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    if n_devices > 1:
        try:
            jax.config.update("jax_num_cpu_devices", n_devices)
        except AttributeError:
            pass  # older jax: the XLA flag above provides the devices


def run_chaos():
    """``--chaos``: the CI chaos harness (docs/RESILIENCE.md §6) — a
    seeded fault scenario on the forced 8-virtual-device CPU mesh over a
    small partitioned dataset, gating the device-fault-tolerance
    invariants: (1) a failed device's partitions reassign and the
    recovered result is BIT-IDENTICAL to the healthy oracle; (2)
    exhausted retries degrade typed with exact survivor totals; (3) a
    killed pool dispatcher slot respawns within one scheduling round;
    (4) nothing hangs (the watchdog would kill us). One JSON line, like
    --smoke."""
    _arm_watchdog()
    _force_cpu(int(os.environ.get("GEOMESA_BENCH_DEVICES", 8)))
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import jax

    from geomesa_tpu import GeoDataset, config, metrics, resilience
    from geomesa_tpu.filter.ecql import parse_iso_ms
    from geomesa_tpu.parallel import health as phealth
    from geomesa_tpu.resilience import InjectedFault, allow_partial, \
        inject_faults

    seed = int(os.environ.get("GEOMESA_BENCH_CHAOS_SEED", 42))
    n = int(os.environ.get("GEOMESA_BENCH_N", 60_000))
    rng = np.random.default_rng(seed)
    lo = parse_iso_ms("2020-01-01")
    hi = parse_iso_ms("2020-03-01")
    ds = GeoDataset(n_shards=4)
    ds.create_schema(
        "chaos", "weight:Float,dtg:Date,*geom:Point;geomesa.partition='time'"
    )
    ds._store("chaos").max_resident = 1
    t0 = time.time()
    ds.insert("chaos", {
        "geom__x": rng.uniform(-125, -66, n),
        "geom__y": rng.uniform(24, 49, n),
        "dtg": rng.integers(lo, hi, n).astype("datetime64[ms]"),
        "weight": rng.uniform(0, 1, n).astype(np.float32),
    })
    ds.flush()
    ingest_s = time.time() - t0
    ecql = "BBOX(geom, -110, 28, -75, 48)"
    bbox = (-125.0, 24.0, -66.0, 50.0)

    def _ctr(name):
        return metrics.registry().counter(name).value

    # healthy oracle (single-device serial path — the bit-identity ref)
    with config.MESH_DEVICES.scoped("off"):
        c_ref = ds.count("chaos", ecql)
        d_ref = ds.density("chaos", ecql, bbox=bbox, width=64, height=64)
    hung = 0
    t0 = time.time()
    # (1) one of 8 devices fails every dispatch: reassign + bit-identity
    reassigned0 = _ctr(metrics.SCAN_REASSIGNED)
    with config.FAULT_INJECTION.scoped("true"), \
            config.RETRY_BASE_MS.scoped("0"), inject_faults(seed=seed) as inj:
        inj.fail("scan.device.dispatch", InjectedFault("dead lane"),
                 times=None, where=lambda c: c.get("device") == 3)
        c_chaos = ds.count("chaos", ecql)
        d_chaos = ds.density("chaos", ecql, bbox=bbox, width=64, height=64)
        lane_fired = len(inj.fired)
    bit_identical = (c_chaos == c_ref) and bool(np.array_equal(d_chaos, d_ref))
    assert bit_identical, (
        f"chaos recovery NOT bit-identical: count {c_chaos} vs {c_ref}"
    )
    reassigned = _ctr(metrics.SCAN_REASSIGNED) - reassigned0
    # (2) a partition failing on EVERY device: exact survivor totals
    st = ds._store("chaos")
    bins = sorted(st.part_counts)
    dead = bins[len(bins) // 2]
    total = ds.count("chaos", "INCLUDE")
    with config.FAULT_INJECTION.scoped("true"), \
            config.RETRY_BASE_MS.scoped("0"), inject_faults(seed=seed) as inj:
        inj.fail("scan.device.dispatch", InjectedFault("bad partition"),
                 times=None, where=lambda c: c.get("bin") == dead)
        with allow_partial() as partial:
            survivors = ds.count("chaos", "INCLUDE")
    survivor_exact = survivors == total - st.part_counts[dead] \
        and len(partial.skipped) == 1
    assert survivor_exact, (survivors, total, st.part_counts[dead])
    phealth.reset()
    resilience.reset_breakers()
    # (3) kill one pool dispatcher slot; the supervisor respawns it
    died0 = _ctr(metrics.SERVING_SLOT_DIED)
    resp0 = _ctr(metrics.SERVING_SLOT_RESPAWN)
    with config.SERVING_EXECUTORS.scoped("2"), \
            config.FAULT_INJECTION.scoped("true"), \
            inject_faults(seed=seed) as inj:
        inj.fail("serving.slot.loop", lambda: SystemExit("chaos kill"),
                 times=1, where=lambda c: c.get("slot") == 1)
        s = ds.serving.start()
        try:
            for _ in range(500):
                if _ctr(metrics.SERVING_SLOT_DIED) > died0:
                    break
                time.sleep(0.01)
            slot_died = _ctr(metrics.SERVING_SLOT_DIED) - died0
            s.submit(lambda: ds.count("chaos", ecql),
                     user="chaos", op="count").result(timeout=60)
            pool_width = s.snapshot()["executors"]
            respawns = _ctr(metrics.SERVING_SLOT_RESPAWN) - resp0
        finally:
            s.stop()
    chaos_s = time.time() - t0
    assert slot_died >= 1 and respawns >= 1 and pool_width == 2, (
        slot_died, respawns, pool_width
    )
    print(json.dumps({
        "metric": "chaos_suite",
        **_device_baseline("forced-cpu-mesh (chaos harness)"),
        "chaos": True,
        "seed": seed,
        "n_rows": n,
        "n_devices": len(jax.devices()),
        "ingest_s": round(ingest_s, 2),
        "chaos_s": round(chaos_s, 2),
        "hung_queries": hung,
        "bit_identical_after_reassign": bit_identical,
        "reassigned_partitions": int(reassigned),
        "lane_faults_fired": int(lane_fired),
        "survivor_totals_exact": survivor_exact,
        "degraded_partitions": len(partial.skipped),
        "slot_died": int(slot_died),
        "slot_respawns": int(respawns),
        "pool_width_after_respawn": int(pool_width),
    }))


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_replica(root: str, rid: str, port: int, extra_env=None):
    """One replica sidecar SUBPROCESS over the shared root (the CLI
    ``fleet replica`` entry — a real separate process, not a thread)."""
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["GEOMESA_CACHE_ENABLED"] = "true"
    env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-m", "geomesa_tpu.cli", "fleet", "replica",
         "--root", root, "--replica-id", rid, "--port", str(port)],
        env=env, cwd=here,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def _wait_replica(port: int, timeout_s: float = 60.0):
    from geomesa_tpu.sidecar import GeoFlightClient

    deadline = time.time() + timeout_s
    last = None
    while time.time() < deadline:
        try:
            with GeoFlightClient(f"grpc+tcp://127.0.0.1:{port}") as c:
                c.version()
            return
        except Exception as e:
            last = e
            time.sleep(0.25)
    raise RuntimeError(f"replica on :{port} never came up: {last!r}")


def run_fleet():
    """``--fleet``: the fleet-smoke harness (docs/RESILIENCE.md §7) —
    router + 2 replica SUBPROCESSES on localhost over one shared root,
    gating: (1) routed-vs-single-process bit-identity across the mixed
    aggregate workload; (2) cell-affinity warm-hit ratio beats random
    routing; (3) SIGKILL of one replica mid-run — every query completes
    via failover within the retry budget, zero hangs, zero partials;
    (4) fleet_qps_scaleup (router+2 replicas vs the same router shape
    over 1 replica). One JSON line, like --smoke. CPU numbers — the
    device-baseline annotation rides along (the BENCH_r04+ precedent)."""
    import tempfile
    import threading

    _arm_watchdog()
    _force_cpu(0)
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from geomesa_tpu import GeoDataset, config, resilience
    from geomesa_tpu.fleet import FleetRouter
    from geomesa_tpu.sidecar import GeoFlightClient

    seed = int(os.environ.get("GEOMESA_BENCH_FLEET_SEED", 7))
    n = int(os.environ.get("GEOMESA_BENCH_N", 60_000))
    rng = np.random.default_rng(seed)
    root = tempfile.mkdtemp(prefix="geomesa-fleet-")
    # default (device) execution path, SAME as the replica subprocesses
    # run: routed-vs-single-process bit-identity is device-vs-device
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "name:String:index=true,dtg:Date,*geom:Point")
    t0 = time.time()
    ds.insert("t", {
        "name": [f"n{i % 8}" for i in range(n)],
        "dtg": (np.datetime64("2024-04-01", "ms")
                + rng.integers(0, 30 * 86_400_000, n)),
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
    }, fids=np.arange(n).astype(str))
    ds.flush("t")
    ds.save(root)
    ingest_s = time.time() - t0

    # the mixed warm workload: distinct viewports, revisited — affinity
    # keeps each one's whole-result entry hot on ONE replica
    vrng = np.random.default_rng(seed + 1)
    views = []
    for _ in range(6):
        x0 = float(vrng.uniform(-118, -90))
        y0 = float(vrng.uniform(26, 40))
        views.append((f"BBOX(geom, {x0}, {y0}, {x0 + 14}, {y0 + 7})",
                      (x0, y0, x0 + 14, y0 + 7)))
    oracle = {
        e: {"count": ds.count("t", e),
            "density": ds.density("t", e, bbox=b, width=48, height=48),
            "stats": ds.stats("t", "MinMax(dtg)", e).to_json()}
        for e, b in views
    }

    def _hit_ratio(clients) -> float:
        hit = miss = 0
        for c in clients:
            m = c.metrics()
            hit += m.get("cache.hit", 0) + m.get("cache.partial", 0)
            miss += m.get("cache.miss", 0)
        return hit / max(hit + miss, 1)

    def _mixed(run_count, run_density, run_stats, rounds=3):
        for _ in range(rounds):
            for e, b in views:
                assert run_count(e) == oracle[e]["count"], e
                got = run_density(e, b)
                assert np.array_equal(got, oracle[e]["density"]), e
                assert run_stats(e) == oracle[e]["stats"], e

    # -- phase R: RANDOM routing baseline (fresh replicas) -----------------
    ports_r = [_free_port(), _free_port()]
    procs_r = [_spawn_replica(root, f"x{i}", p)
               for i, p in enumerate(ports_r)]
    random_ratio = 0.0
    try:
        for p in ports_r:
            _wait_replica(p)
        clients_r = [GeoFlightClient(f"grpc+tcp://127.0.0.1:{p}")
                     for p in ports_r]
        pick = np.random.default_rng(seed + 2)
        _mixed(
            lambda e: clients_r[pick.integers(2)].count("t", e),
            lambda e, b: clients_r[pick.integers(2)].density(
                "t", e, bbox=b, width=48, height=48),
            lambda e: clients_r[pick.integers(2)].stats(
                "t", "MinMax(dtg)", e).to_json(),
        )
        random_ratio = _hit_ratio(clients_r)
        for c in clients_r:
            c.close()
    finally:
        for p in procs_r:
            p.kill()
    resilience.reset_breakers()

    # -- phase F: the fleet (router + 2 fresh replica subprocesses) --------
    ports = [_free_port(), _free_port()]
    procs = [_spawn_replica(root, f"r{i + 1}", p)
             for i, p in enumerate(ports)]
    try:
        for p in ports:
            _wait_replica(p)
        router = FleetRouter({
            f"r{i + 1}": f"grpc+tcp://127.0.0.1:{p}"
            for i, p in enumerate(ports)
        })
        router1 = FleetRouter({"r1": f"grpc+tcp://127.0.0.1:{ports[0]}"})
        # warm mixed workload through cell-affinity routing
        _mixed(
            lambda e: router.count("t", e),
            lambda e, b: router.density("t", e, bbox=b, width=48,
                                        height=48),
            lambda e: router.stats("t", "MinMax(dtg)", e).to_json(),
        )
        affinity_clients = [router._client(r)
                            for r in router.registry.members()]
        affinity_ratio = _hit_ratio(affinity_clients)

        # qps scale-up: same router code path, 1 vs 2 replicas, FRESH
        # (uncached) viewports so the replicas do real scan work
        def _qps(r, tag, threads=4, per=6):
            qrng = np.random.default_rng(seed + 3)
            batches = []
            for t in range(threads):
                mine = []
                for k in range(per):
                    x0 = float(qrng.uniform(-118, -90))
                    y0 = float(qrng.uniform(26, 40))
                    mine.append(
                        f"(name = 'n{(t + k) % 8}') AND BBOX(geom, "
                        f"{x0}, {y0}, {x0 + 11}, {y0 + 6})"
                    )
                batches.append(mine)
            errs = []

            def work(mine):
                try:
                    for e in mine:
                        r.count("t", e + f" AND name <> '{tag}'")
                except Exception as exc:  # pragma: no cover
                    errs.append(exc)

            ths = [threading.Thread(target=work, args=(m,))
                   for m in batches]
            t1 = time.perf_counter()
            for th in ths:
                th.start()
            for th in ths:
                th.join(timeout=300)
            assert not errs, errs
            return threads * per / (time.perf_counter() - t1)

        qps1 = _qps(router1, "q1")
        qps2 = _qps(router, "q2")
        scaleup = qps2 / max(qps1, 1e-9)

        # -- phase S: scatter-vs-whole on cold fleet-wide aggregates ----
        # (ISSUE 15): density / stats / curve / count scattered across
        # both owners vs routed whole to one. Fresh name-residuals dodge
        # every cache (same rows scanned either way); a warmup pass per
        # mode pays kernel compiles outside the timed window; two timed
        # rounds with mode order swapped, min per mode.
        wide = "BBOX(geom, -119.5, 25.5, -70.5, 49.5)"
        wide_bbox = (-120.0, 25.0, -70.0, 50.0)

        def _cold(tag):
            return f"(name <> 'zz{tag}') AND {wide}"

        e_bi = _cold("bi")
        g_sc = router.density("t", e_bi, bbox=wide_bbox, width=96,
                              height=64)
        g_ds = ds.density("t", e_bi, bbox=wide_bbox, width=96, height=64)
        scatter_bit = bool(np.array_equal(g_sc, g_ds))
        scatter_bit &= (
            router.stats("t", "MinMax(dtg)", e_bi).to_json()
            == ds.stats("t", "MinMax(dtg)", e_bi).to_json()
        )
        gc, snc = router.density_curve("t", e_bi, level=6, bbox=wide_bbox)
        gd, snd = ds.density_curve("t", e_bi, level=6, bbox=wide_bbox)
        scatter_bit &= bool(tuple(snc) == tuple(snd)
                            and np.array_equal(gc, gd))
        scatter_bit &= router.count("t", e_bi) == ds.count("t", e_bi)
        assert scatter_bit, "scattered aggregate diverged from oracle"
        snap_s = router.snapshot()
        assert snap_s["counters"]["scatter"] >= 4, snap_s["counters"]

        def _run_kind(kind, e):
            if kind == "density":
                router.density("t", e, bbox=wide_bbox, width=96,
                               height=64)
            else:
                router.stats("t", "MinMax(dtg)", e)

        def _timed(kind, scatter_on, tag):
            knob = "true" if scatter_on else "false"
            with config.FLEET_SCATTER.scoped(knob):
                _run_kind(kind, _cold(f"w{tag}"))  # warmup: compiles
                t1 = time.perf_counter()
                _run_kind(kind, _cold(tag))
                return time.perf_counter() - t1

        speedup = {}
        for kind in ("density", "stats"):
            times = {True: [], False: []}
            for rnd in range(2):
                order = [True, False] if rnd % 2 == 0 else [False, True]
                for mode in order:
                    times[mode].append(
                        _timed(kind, mode, f"{kind[0]}{rnd}{int(mode)}")
                    )
            speedup[kind] = min(times[False]) / max(min(times[True]), 1e-9)

        # SIGKILL one replica mid-run: the chaos half of the gate
        victim = router.ring.owner(f"schema:t")
        procs[int(victim[1]) - 1].kill()
        failover_ms = 0.0
        hung = 0
        from geomesa_tpu.resilience import QueryTimeoutError

        with config.RETRY_ATTEMPTS.scoped("2"):
            for e, b in views:
                t1 = time.perf_counter()
                try:
                    with resilience.deadline_scope(30.0):
                        got = router.count("t", e)
                        g = router.density("t", e, bbox=b, width=48,
                                           height=48)
                except QueryTimeoutError:
                    # MEASURED, not assumed: a post-kill query that
                    # burned its whole 30 s budget counts as hung (the
                    # deadline is what turned the hang into an error)
                    hung += 1
                    continue
                dt = (time.perf_counter() - t1) * 1e3
                failover_ms = max(failover_ms, dt)
                assert got == oracle[e]["count"], (
                    f"post-kill count wrong for {e}: {got}"
                )
                assert np.array_equal(g, oracle[e]["density"]), e
        assert hung == 0, f"{hung} post-kill queries burned their budget"
        snap = router.snapshot()
        assert snap["counters"]["failover"] >= 1, snap["counters"]
        partials = snap["counters"]["partial"]
        assert partials == 0, snap["counters"]
        router.close()
        router1.close()
    finally:
        for p in procs:
            try:
                p.kill()
            except Exception:
                pass

    import multiprocessing

    cores = multiprocessing.cpu_count()
    out = {
        "metric": "fleet_suite",
        "fleet": True,
        "seed": seed,
        "n_rows": n,
        "ingest_s": round(ingest_s, 2),
        "fleet_bit_identical": True,  # hard-asserted above, per query
        "fleet_hung_queries": hung,
        "fleet_partials": int(partials),
        "fleet_failover_ms": round(failover_ms, 1),
        "fleet_affinity_hit_ratio": round(affinity_ratio, 3),
        "fleet_random_hit_ratio": round(random_ratio, 3),
        "fleet_qps_1replica": round(qps1, 1),
        "fleet_qps_2replicas": round(qps2, 1),
        "fleet_qps_scaleup": round(scaleup, 2),
        # scatter-gather (ISSUE 15): cold fleet-wide mergeable aggregates
        # split across owner groups vs routed whole to one replica —
        # bit-identity hard-asserted above across all four kinds
        "fleet_scatter_bit_identical": scatter_bit,
        "fleet_scatter_density_speedup": round(speedup["density"], 2),
        "fleet_scatter_stats_speedup": round(speedup["stats"], 2),
        "fleet_counters": snap["counters"],
        # CPU numbers: the device-baseline gap annotation carried
        # forward from the main bench (BENCH_r04+ precedent)
        "device_unreachable": True,
        "probe_skipped": True,
        **_device_baseline("forced-cpu-mesh (fleet harness)"),
    }
    if cores < 4:
        # router + 2 replica processes + client threads cannot express
        # real parallelism below ~4 cores: the scale-up gate conditions
        # on this, exactly like the sharded/pool gates
        out["parallel_headroom_limited"] = True
    assert affinity_ratio > random_ratio, (
        f"affinity routing ({affinity_ratio:.3f}) did not beat random "
        f"routing ({random_ratio:.3f})"
    )
    print(json.dumps(out))


def run_fleet_obs():
    """``--fleet-obs``: the fleet observability plane harness
    (docs/OBSERVABILITY.md §9) — router + 3 replica SUBPROCESSES over
    one shared root, gating: (1) federated counters are EXACT sums of
    independently pulled per-replica values; (2) one scattered query
    stitches into ONE span tree with replica subtrees from >= 2
    replicas; (3) /debug/heat is non-empty after a viewport workload;
    (4) a federation loop hammering metrics-export adds < 5% to the
    warm requery median — the plane is pull/async, never on the query
    path. One JSON line, like --fleet."""
    import statistics
    import tempfile
    import threading

    _arm_watchdog()
    _force_cpu(0)
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from geomesa_tpu import GeoDataset, config, obs, tracing
    from geomesa_tpu.fleet import FleetRouter
    from geomesa_tpu.sidecar import GeoFlightClient

    seed = int(os.environ.get("GEOMESA_BENCH_FLEET_SEED", 7))
    n = int(os.environ.get("GEOMESA_BENCH_N", 40_000))
    rng = np.random.default_rng(seed)
    root = tempfile.mkdtemp(prefix="geomesa-fleet-obs-")
    ds = GeoDataset(n_shards=2)
    ds.create_schema("t", "name:String:index=true,dtg:Date,*geom:Point")
    ds.insert("t", {
        "name": [f"n{i % 8}" for i in range(n)],
        "dtg": (np.datetime64("2024-04-01", "ms")
                + rng.integers(0, 30 * 86_400_000, n)),
        "geom__x": rng.uniform(-120, -70, n),
        "geom__y": rng.uniform(25, 50, n),
    }, fids=np.arange(n).astype(str))
    ds.flush("t")
    ds.save(root)
    wide = [
        "BBOX(geom, -119, 26, -72, 49)",
        "BBOX(geom, -118, 27, -74, 48)",
        "BBOX(geom, -117, 26, -73, 47)",
    ]
    views = []
    vrng = np.random.default_rng(seed + 1)
    for _ in range(5):
        x0 = float(vrng.uniform(-118, -90))
        y0 = float(vrng.uniform(26, 40))
        views.append(f"BBOX(geom, {x0}, {y0}, {x0 + 12}, {y0 + 6})")
    oracle = {e: ds.count("t", e) for e in wide + views}

    ports = [_free_port() for _ in range(3)]
    procs = [
        _spawn_replica(root, f"r{i + 1}", p,
                       extra_env={"GEOMESA_TRACE_ENABLED": "true"})
        for i, p in enumerate(ports)
    ]
    try:
        for p in ports:
            _wait_replica(p)
        router = FleetRouter({
            f"r{i + 1}": f"grpc+tcp://127.0.0.1:{p}"
            for i, p in enumerate(ports)
        })
        plane = router.observability()

        # viewport workload: cold decompositions feed each replica's
        # heat table; the repeats warm the caches for the overhead gate
        for _ in range(3):
            for e in views:
                assert router.count("t", e) == oracle[e], e

        # -- gate 2: one scattered query -> ONE stitched span tree ------
        stitched = None
        with config.TRACE_ENABLED.scoped("true"):
            for e in wide:
                assert router.count("t", e) == oracle[e], e
                tid = tracing.last_trace().trace_id
                deadline = time.time() + 20.0
                while time.time() < deadline:
                    rec = plane.stitched(tid)
                    if rec is not None:
                        break
                    time.sleep(0.1)
                assert rec is not None, f"trace {tid} never stitched"
                if len(rec["replicas"]) >= 2:
                    stitched = rec
                    break
        assert stitched is not None, "no scattered query spanned 2 replicas"
        assert stitched["subtrees"] >= 2, stitched["subtrees"]
        code, _, _ = obs.handle(f"/debug/queries?trace={stitched['trace_id']}")
        assert code == 200, code

        # -- gate 1: merged counters are EXACT per-replica sums ----------
        # pull each replica's registry independently, THEN federate: the
        # cache counters are quiesced (no queries in flight), so the
        # merged values must equal the manual sums to the integer
        sums = {"cache.hit": 0, "cache.miss": 0}
        for i, p in enumerate(ports):
            with GeoFlightClient(f"grpc+tcp://127.0.0.1:{p}") as c:
                m = c.metrics()
                for k in sums:
                    sums[k] += int(m.get(k, 0))
        fed = plane.federate(force=True)
        assert fed["errors"] == {}, fed["errors"]
        assert len(fed["replicas"]) == 3, fed["replicas"]
        merged = fed["merged"]["counters"]
        counters_exact = all(int(merged.get(k, 0)) == v and v > 0
                             for k, v in sums.items())
        assert counters_exact, (dict(sums), {k: merged.get(k) for k in sums})

        # -- gate 3: the fleet heat view is non-empty --------------------
        heat_rows = plane.fleet_heat(top=32)["schemas"]
        assert heat_rows.get("t"), heat_rows
        code, _, body = obs.handle("/debug/heat?top=32")
        assert code == 200 and b'"t"' in body, code

        # -- gate 4: federation adds < 5% to the warm requery median -----
        # the scraper below polls 10x harder than the TTL it runs under
        # (20 scrapes/s, pulls gated to 2/s — 4x the default cadence);
        # the TTL cache is exactly the mechanism that bounds scrape
        # load, so the gate measures the designed path: a pull is never
        # ON a query, only beside it
        def _warm_block(pool, samples=50):
            for i in range(samples):
                e = views[i % len(views)]
                t1 = time.perf_counter()
                assert router.count("t", e) == oracle[e], e
                pool.append(time.perf_counter() - t1)

        stop = threading.Event()
        scraping = threading.Event()

        def _scraper():
            while not stop.is_set():
                if scraping.is_set():
                    try:
                        plane.federate()
                    except Exception:
                        pass
                stop.wait(0.05)

        # env, not .scoped(): the override must be visible ON the
        # scraper thread (scoped overrides are thread-local)
        os.environ["GEOMESA_FLEET_OBS_TTL_MS"] = "500"
        th = threading.Thread(target=_scraper, daemon=True)
        th.start()
        base_lat, under_lat = [], []
        try:
            # interleaved A/B blocks: machine drift between phases lands
            # on both pools equally, so the delta isolates federation
            for _ in range(8):
                scraping.clear()
                _warm_block(base_lat)
                scraping.set()
                _warm_block(under_lat)
        finally:
            stop.set()
            th.join(timeout=5)
            os.environ.pop("GEOMESA_FLEET_OBS_TTL_MS", None)

        def _trimmed(lat):
            # interquartile mean: a federation pull coinciding with a
            # block can contaminate ~10% of its samples on a starved
            # box; the 25% trim keeps the estimate on the typical query
            lat = sorted(lat)
            k = len(lat) // 4
            return statistics.fmean(lat[k:len(lat) - k])

        base_s = _trimmed(base_lat)
        under_s = _trimmed(under_lat)
        overhead_pct = max(under_s - base_s, 0.0) / base_s * 100.0
        router.close()
    finally:
        for p in procs:
            try:
                p.kill()
            except Exception:
                pass

    print(json.dumps({
        "metric": "fleet_obs_suite",
        "fleet_obs": True,
        "seed": seed,
        "n_rows": n,
        "fleet_obs_counters_exact": counters_exact,
        "fleet_obs_stitched_replicas": len(stitched["replicas"]),
        "fleet_obs_stitched_subtrees": int(stitched["subtrees"]),
        "fleet_obs_heat_rows": len(heat_rows["t"]),
        "fleet_obs_warm_ms": round(base_s * 1e3, 3),
        "fleet_obs_warm_under_federation_ms": round(under_s * 1e3, 3),
        "fleet_obs_federation_overhead_pct": round(overhead_pct, 2),
        "device_unreachable": True,
        "probe_skipped": True,
        **_device_baseline("forced-cpu-mesh (fleet obs harness)"),
    }))


def run_crash():
    """``--crash``: the CI crash-durability harness (docs/RESILIENCE.md
    §8) — gating the journal's three promises on the forced-CPU backend:
    (1) ``journal_acked_lost == 0`` — a writer subprocess is SIGKILLed
    mid-ingest and every insert it acked (journal append returned) must
    survive recovery; (2) ``journal_insert_overhead_pct`` — group-commit
    durability stays within budget of the non-durable insert path under
    the design-point load of a few concurrent writers (the commit
    leader's fsync releases the GIL, so followers encode while it
    syncs and ride the next leader's batch); (3)
    ``journal_recovery_ms`` — replay cost of an un-checkpointed tail.
    One JSON line, like --chaos."""
    import shutil
    import subprocess
    import tempfile
    import threading

    _arm_watchdog()
    _force_cpu(int(os.environ.get("GEOMESA_BENCH_DEVICES", 8)))
    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, here)
    from geomesa_tpu import GeoDataset
    from geomesa_tpu.filter.ecql import parse_iso_ms

    seed = int(os.environ.get("GEOMESA_BENCH_CRASH_SEED", 42))
    n = int(os.environ.get("GEOMESA_BENCH_N", 131_072))
    batch = 4_096
    writers = int(os.environ.get("GEOMESA_BENCH_CRASH_WRITERS", 4))
    lo = parse_iso_ms("2020-01-01")
    hi = parse_iso_ms("2020-03-01")
    spec = "name:String,weight:Float,dtg:Date,*geom:Point"
    schemas = [f"t{w}" for w in range(writers)]

    def _batches(w, nw):
        rng = np.random.default_rng(seed + w)
        for s in range(0, nw, batch):
            m = min(batch, nw - s)
            yield {
                "name": [f"w{w}r{s + i}" for i in range(m)],
                "weight": rng.uniform(0, 1, m).astype(np.float32),
                "dtg": rng.integers(lo, hi, m).astype("datetime64[ms]"),
                "geom__x": rng.uniform(-125, -66, m),
                "geom__y": rng.uniform(24, 49, m),
            }

    def _ingest(journal_root):
        # one writer thread per schema (insert touches only per-schema
        # store state; the journal itself is thread-safe) — identical
        # shape for the plain and journaled runs, so the delta is pure
        # durability cost
        ds = GeoDataset(prefer_device=False)
        if journal_root is not None:
            ds.attach_journal(journal_root)
        for nm in schemas:
            ds.create_schema(nm, spec)
        errs = []

        def _writer(w):
            try:
                for data in _batches(w, n // writers):
                    ds.insert(schemas[w], data)
            except BaseException as e:  # surface, don't hang the join
                errs.append(e)

        t0 = time.time()
        ts = [threading.Thread(target=_writer, args=(w,))
              for w in range(writers)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        ds.flush()
        if errs:
            raise errs[0]
        return ds, time.time() - t0

    work = tempfile.mkdtemp(prefix="gm-crash-")
    try:
        # (2) insert overhead: non-durable baseline vs journaled (warmup
        # pass first so jit/alloc costs don't ride either side)
        _ingest(None)
        _, t_plain = _ingest(None)
        jroot = os.path.join(work, "journaled")
        os.makedirs(jroot)
        ds_j, t_journal = _ingest(jroot)
        overhead_pct = (t_journal - t_plain) / t_plain * 100.0

        # (3) recovery: load the root with its whole ingest un-checkpointed
        t0 = time.time()
        ds_r = GeoDataset.load(jroot, prefer_device=False)
        recovery_ms = (time.time() - t0) * 1000.0
        replayed = ds_r._journal_replayed
        assert sum(ds_r.count(nm) for nm in schemas) == \
            sum(ds_j.count(nm) for nm in schemas), "recovery lost rows"

        # (1) SIGKILL a writer subprocess mid-ingest; every acked insert
        # must survive recovery (ack = the mutation call returned)
        kroot = os.path.join(work, "killed")
        os.makedirs(kroot)
        child_src = (
            "import os, sys\n"
            f"sys.path.insert(0, {here!r})\n"
            "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            "import numpy as np\n"
            "from geomesa_tpu import GeoDataset\n"
            f"root = {kroot!r}\n"
            "ds = GeoDataset(prefer_device=False)\n"
            "ds.attach_journal(root)\n"
            "ds.create_schema('t', "
            f"{spec!r})\n"
            "ack = open(os.path.join(root, 'acked.log'), 'a')\n"
            "i = 0\n"
            "print('READY', flush=True)\n"
            "while True:\n"
            "    ds.insert('t', {'name': [f'k{i}'], 'weight': [0.5],\n"
            "                    'dtg': np.array([1577836800000],\n"
            "                                    'datetime64[ms]'),\n"
            "                    'geom__x': [0.0], 'geom__y': [0.0]})\n"
            "    ack.write(f'k{i}\\n'); ack.flush()\n"
            "    os.fsync(ack.fileno())\n"
            "    i += 1\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-c", child_src], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        )
        assert proc.stdout.readline().strip() == "READY"
        time.sleep(2.0)  # let it ack a pile of inserts
        proc.kill()
        proc.wait()
        with open(os.path.join(kroot, "acked.log")) as fh:
            acked = set(fh.read().split())
        ds_k = GeoDataset.load(kroot, prefer_device=False)
        got = set(
            "" if v is None else str(v)
            for v in ds_k.to_arrow("t").column("name").to_pylist()
        )
        lost = sorted(acked - got)
        assert not lost, f"SIGKILL lost {len(lost)} acked inserts: {lost[:5]}"
    finally:
        shutil.rmtree(work, ignore_errors=True)

    print(json.dumps({
        "metric": "crash_suite",
        "crash": True,
        "seed": seed,
        "n_rows": n,
        "journal_insert_overhead_pct": round(overhead_pct, 1),
        "journal_recovery_ms": round(recovery_ms, 1),
        "journal_replayed_records": int(replayed),
        "journal_acked_lost": len(lost),
        "killed_acked_inserts": len(acked),
        "killed_recovered_inserts": len(got),
        "device_unreachable": True,
        "probe_skipped": True,
        **_device_baseline("forced-cpu-mesh (crash harness)"),
    }))


def main():
    if "--chaos" in sys.argv[1:]:
        return run_chaos()
    if "--fleet-obs" in sys.argv[1:]:
        return run_fleet_obs()
    if "--fleet" in sys.argv[1:]:
        return run_fleet()
    if "--crash" in sys.argv[1:]:
        return run_crash()
    smoke = "--smoke" in sys.argv[1:]
    n = int(os.environ.get("GEOMESA_BENCH_N", 200_000 if smoke else 20_000_000))
    iters = int(os.environ.get("GEOMESA_BENCH_ITERS", 2 if smoke else 10))
    _arm_watchdog()
    annotations = {}
    cpu_backend = smoke
    if smoke:
        # CI mode: tiny dataset, no probe, forced CPU with a virtual
        # 8-device mesh — the warm-path AND multi-device keys below
        # regress-test the executor without TPU access
        annotations["smoke"] = True
        _force_cpu(int(os.environ.get("GEOMESA_BENCH_DEVICES", 8)))
    else:
        probe_failure = _probe_device()
        if probe_failure is not None:
            # Accelerator unreachable: fall back to the CPU backend and
            # measure REAL numbers instead of emitting value: 0 with rc=3
            # (the BENCH_r05 failure mode — a zeroed metric poisons the
            # round's trajectory). "device_unreachable": true rides along
            # as an annotation so the driver knows these are CPU numbers.
            sys.stderr.write(
                "accelerator unreachable: falling back to JAX_PLATFORMS=cpu "
                "(annotated, not zeroed)\n"
            )
            annotations.update(probe_failure)
            cpu_backend = True
            _force_cpu()

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from geomesa_tpu import GeoDataset
    from geomesa_tpu.filter.ecql import parse_iso_ms

    # Above this size (or with GEOMESA_BENCH_PARTITIONED=1) the dataset is
    # time-partitioned and out-of-core: cold partitions spill to disk and
    # queries stream the pruned partitions through RAM/HBM (the 1B-point
    # architecture; see docs/SCALE.md for the memory-budget arithmetic).
    partitioned = n >= int(
        os.environ.get("GEOMESA_BENCH_PART_THRESHOLD", 50_000_000)
    ) or os.environ.get("GEOMESA_BENCH_PARTITIONED") == "1"

    rng = np.random.default_rng(7)
    t0 = time.time()
    # GDELT-like point events across CONUS at a constant event rate of
    # ~20M/month (so n=20M reproduces earlier rounds exactly, and larger n
    # extends the time axis the way real feeds do — the partition-pruning
    # story then matches production shape: a 10-day query window over a
    # long-running feed)
    # never shrink below one month: the fixed Jan-05/15 query window must
    # keep matching rows at small n (the --smoke dataset), or the bench
    # measures empty scans
    span_ms = int(
        (parse_iso_ms("2020-02-01") - parse_iso_ms("2020-01-01"))
        * max(n / 20_000_000, 1.0)
    )
    lo_ms = parse_iso_ms("2020-01-01")
    data = {
        "geom__x": rng.uniform(-125, -66, n),
        "geom__y": rng.uniform(24, 49, n),
        "dtg": rng.integers(lo_ms, lo_ms + span_ms, n).astype("datetime64[ms]"),
        "weight": rng.uniform(0, 1, n).astype(np.float32),
    }
    gen_s = time.time() - t0

    spec = "weight:Float,dtg:Date,*geom:Point"
    if partitioned:
        spec += ";geomesa.partition='time'"
    ds = GeoDataset(n_shards=8)
    ds.create_schema("gdelt", spec)
    t0 = time.time()
    # chunked ingest: the encoder never materializes more than one chunk of
    # fid strings at a time; the partitioned flush indexes one partition at
    # a time under the residency budget
    chunk = int(os.environ.get("GEOMESA_BENCH_CHUNK", 25_000_000))
    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        ds.insert(
            "gdelt",
            {k: v[lo:hi] for k, v in data.items()},
            fids=np.arange(lo, hi).astype(str),
        )
    ds.flush("gdelt")
    ingest_s = time.time() - t0

    ecql = (
        "BBOX(geom, -100, 30, -80, 45) AND "
        "dtg DURING 2020-01-05T00:00:00Z/2020-01-15T00:00:00Z"
    )
    bbox = (-100.0, 30.0, -80.0, 45.0)
    W = H = 512

    # plan once; executor caches the jitted kernel on the plan
    st, _, plan = ds._plan("gdelt", ecql)
    ex = ds._executor(st)

    # device path: warmup (compile + window upload) then steady-state.
    # Results stay on device inside the loop (as in a real pipeline where
    # grids feed further device-side composition or ride PCIe); the best
    # iteration is reported to reject host-link latency spikes, which on
    # tunneled dev setups can exceed the kernel time by 100x.
    import jax

    import jax.numpy as jnp

    # Honest device timing over the tunneled chip. Two facts force the
    # method: (a) jax.block_until_ready over the axon tunnel acks enqueue,
    # not execution — timing it reports dispatch (the "0.2ms kernels" of
    # earlier rounds were fiction; a 1 GiB reduction "completed" in 20us,
    # 50x the physical HBM bandwidth); (b) a host fetch IS execution-
    # dependent but costs a ~25-70ms round trip. So: time a chain of k
    # data-dependent query executions ending in one scalar fetch, for two
    # chain lengths, and difference out the constant round trip:
    #   per_query = (T(k2) - T(k1)) / (k2 - k1)
    def chain(k: int) -> float:
        t0 = time.time()
        acc = None
        for _ in range(k):
            g = ex.density(plan, bbox, W, H, as_numpy=False)
            acc = g if acc is None else acc + g
        float(jnp.sum(acc))  # execution-dependent sync
        return time.time() - t0

    chain(2)  # warmup: compile + column/window upload
    k1 = 2
    k2 = k1 + int(
        os.environ.get("GEOMESA_BENCH_BATCH", 4 if cpu_backend else 32)
    )
    t1 = min(chain(k1) for _ in range(iters))
    t2 = min(chain(k2) for _ in range(iters))
    dev_s = max((t2 - t1) / (k2 - k1), 1e-9)
    grid = np.asarray(ex.density(plan, bbox, W, H, as_numpy=False))

    # p50 END-TO-END density latency (BASELINE.md's second headline):
    # the public API path — plan + window resolution + device scan + host
    # grid transfer — cold-cache planning each call
    e2e = sorted(
        _timed(lambda: ds.density("gdelt", ecql, bbox=bbox, width=W, height=H))
        for _ in range(5)
    )
    p50_e2e_ms = e2e[len(e2e) // 2] * 1e3
    matched = float(grid.sum())

    # CPU baseline: vectorized numpy over the same raw arrays (filter + 2D hist)
    x, y = data["geom__x"], data["geom__y"]
    t = data["dtg"].astype(np.int64)
    lo, hi = parse_iso_ms("2020-01-05"), parse_iso_ms("2020-01-15")
    t0 = time.time()
    cpu_iters = max(1, min(3, iters))
    for _ in range(cpu_iters):
        m = (
            (x >= bbox[0]) & (x <= bbox[2]) & (y >= bbox[1]) & (y <= bbox[3])
            & (t >= lo) & (t <= hi)
        )
        px = np.clip(((x[m] - bbox[0]) / (bbox[2] - bbox[0]) * W).astype(np.int64), 0, W - 1)
        py = np.clip(((y[m] - bbox[1]) / (bbox[3] - bbox[1]) * H).astype(np.int64), 0, H - 1)
        cpu_grid = np.zeros(H * W, np.float32)
        np.add.at(cpu_grid, py * W + px, 1.0)
    cpu_s = (time.time() - t0) / cpu_iters

    # exact: the band certificate guarantees f64 boundary semantics on the
    # device path (r1-r3 silently over-counted one f32-edge row here)
    assert matched == float(m.sum()), (
        f"device {matched} vs cpu {float(m.sum())}"
    )

    during = "dtg DURING 2020-01-05T00:00:00Z/2020-01-15T00:00:00Z"

    def pan_ecql(dx):
        return (
            f"BBOX(geom, {-100 + dx}, 30, {-80 + dx}, 45) AND {during}"
        )

    # Warm-path executor effectiveness (docs/PERF.md): steady state must be
    # compile-free. warm_requery_ms = p50 of the SAME public-API query
    # re-issued (plan cache + kernel registry + window caches warm);
    # recompiles_per_100_queries = fresh jit traces per 100 queries cycling
    # distinct-but-similar filters AFTER one warmup cycle — zero when
    # shape bucketing + version-stable kernel keys hold.
    from geomesa_tpu import metrics as _metrics

    warm = sorted(
        _timed(lambda: ds.density("gdelt", ecql, bbox=bbox, width=W, height=H))
        for _ in range(5)
    )
    warm_requery_ms = warm[len(warm) // 2] * 1e3

    # Tracing overhead (docs/OBSERVABILITY.md): the SAME warm requery with
    # span tracing enabled vs the untraced p50 above. The disabled span API
    # must be a no-op (the ci.yml smoke gate holds trace_overhead_pct of
    # the ENABLED path under 5% — the disabled path rides inside
    # warm_requery_ms itself, so any disabled-path regression shows there).
    from geomesa_tpu import config as _tcfg

    with _tcfg.TRACE_ENABLED.scoped("true"):
        ds.density("gdelt", ecql, bbox=bbox, width=W, height=H)  # warm trace
        traced = sorted(
            _timed(lambda: ds.density("gdelt", ecql, bbox=bbox,
                                      width=W, height=H))
            for _ in range(5)
        )
    traced_ms = traced[len(traced) // 2] * 1e3
    trace_overhead_pct = (
        (traced_ms - warm_requery_ms) / warm_requery_ms * 100.0
        if warm_requery_ms > 0 else 0.0
    )
    sys.stderr.write(
        f"tracing: warm traced p50={traced_ms:.1f}ms vs untraced "
        f"{warm_requery_ms:.1f}ms ({trace_overhead_pct:+.1f}%)\n"
    )

    # Export overhead (docs/OBSERVABILITY.md): the same warm requery with
    # tracing AND the file-sink exporter active, vs the untraced p50 —
    # mirrors trace_overhead_pct, gated < 5% by ci.yml. The export file is
    # left behind (GEOMESA_BENCH_EXPORT_PATH) so CI validates the OTLP
    # span-batch shape of what actually got written.
    export_path = os.environ.get(
        "GEOMESA_BENCH_EXPORT_PATH", "/tmp/_trace_export.jsonl"
    )
    try:
        os.remove(export_path)
    except OSError:
        pass
    from geomesa_tpu import tracing_export as _texp

    with _tcfg.TRACE_ENABLED.scoped("true"), \
            _tcfg.TRACE_EXPORT_PATH.scoped(export_path):
        ds.density("gdelt", ecql, bbox=bbox, width=W, height=H)  # warm
        exporting = sorted(
            _timed(lambda: ds.density("gdelt", ecql, bbox=bbox,
                                      width=W, height=H))
            for _ in range(5)
        )
        _texp.flush()
    exporting_ms = exporting[len(exporting) // 2] * 1e3
    export_overhead_pct = (
        (exporting_ms - warm_requery_ms) / warm_requery_ms * 100.0
        if warm_requery_ms > 0 else 0.0
    )
    sys.stderr.write(
        f"export: warm exporting p50={exporting_ms:.1f}ms vs untraced "
        f"{warm_requery_ms:.1f}ms ({export_overhead_pct:+.1f}%) "
        f"-> {export_path}\n"
    )
    variants = [pan_ecql(dx) for dx in (0.0, 0.5, 1.0, 1.5)]
    for v in variants:  # warmup: at most one trace per distinct filter
        ds.count("gdelt", v)
    _rec = _metrics.registry().counter(_metrics.KERNEL_RECOMPILES)
    rec0 = _rec.value
    n_q = int(os.environ.get("GEOMESA_BENCH_WARM_QUERIES", 100))
    t0 = time.time()
    for i in range(n_q):
        ds.count("gdelt", variants[i % len(variants)])
    warm_count_s = time.time() - t0
    recompiles_per_100 = (_rec.value - rec0) * 100.0 / max(n_q, 1)
    sys.stderr.write(
        f"warm path: requery p50={warm_requery_ms:.1f}ms "
        f"recompiles/100q={recompiles_per_100:.1f} "
        f"({n_q} warm counts in {warm_count_s:.2f}s)\n"
    )

    # Concurrent serving (docs/SERVING.md): N=8 identical-shape count
    # queries, serial vs fused through the scheduler. The fused batch must
    # ACTUALLY fuse — at most 2 device dispatches for the whole batch (the
    # ci.yml smoke gate) — and return bit-identical counts. queue-wait and
    # fusion-batch distributions ride along from the metrics registry.
    serving_keys = {}
    if os.environ.get("GEOMESA_BENCH_SERVING", "1") != "0":
        import threading as _threading

        from geomesa_tpu.serving import fuse as _fuse

        N_FUSE = 8
        serial_counts = []
        ds.count("gdelt", ecql)  # warm (plan + kernel + windows)
        t0 = time.time()
        for _ in range(N_FUSE):
            serial_counts.append(ds.count("gdelt", ecql))
        serving_serial_s = time.time() - t0
        sched = ds.serving.start()
        _disp = _metrics.registry().counter(_metrics.EXEC_DEVICE_DISPATCH)
        gate = _threading.Event()
        stall = sched.submit(lambda: gate.wait(30), user="warm", op="stall")
        opts = {"ecql": ecql}
        futs = [
            sched.submit(
                lambda: ds.count("gdelt", ecql),
                user=f"client{i % 4}", op="count",
                fuse=_fuse.make_spec(ds, "count", "gdelt", opts),
            )
            for i in range(N_FUSE)
        ]
        d0 = _disp.value
        t0 = time.time()
        gate.set()
        fused_counts = [f.result(120) for f in futs]
        serving_fused_s = time.time() - t0
        stall.result(30)
        fused_dispatches = _disp.value - d0
        sched.stop()
        assert fused_counts == serial_counts, (
            f"fused {fused_counts[:2]} != serial {serial_counts[:2]}"
        )
        wait_hist = _metrics.registry().histogram(
            _metrics.SERVING_QUEUE_WAIT
        )
        batch_hist = _metrics.registry().histogram(
            _metrics.SERVING_FUSION_BATCH,
            buckets=_metrics.FUSION_BATCH_BUCKETS, unit=None,
        )
        serving_keys = {
            "concurrent_qps": round(
                N_FUSE / max(serving_fused_s, 1e-9), 1
            ),
            "serving_fused_speedup": round(
                serving_serial_s / max(serving_fused_s, 1e-9), 2
            ),
            "fused_batch_p50": batch_hist.quantile(0.5),
            "fused_dispatches": int(fused_dispatches),
            "queue_wait_p99_ms": round(wait_hist.quantile(0.99) * 1e3, 3),
        }
        sys.stderr.write(
            f"serving: {N_FUSE} identical counts serial="
            f"{serving_serial_s * 1e3:.1f}ms fused="
            f"{serving_fused_s * 1e3:.1f}ms "
            f"dispatches={fused_dispatches} "
            f"batch_p50={serving_keys['fused_batch_p50']}\n"
        )

        # Query-axis megakernel (docs/SERVING.md "Query-axis batching"):
        # N=8 DISTINCT-bbox counts, serial vs one batched device pass
        # through the scheduler's structural fusion. Hard gates (ci.yml):
        # <= 2 device dispatches for the batch and every member
        # bit-identical to its serial execution (the cross-member leak
        # guard). Literals are kernel data — the batch shares one
        # compiled kernel with the warm path, so recompiles stay 0.
        dx0, dy0, dx1, dy1 = bbox
        dw, dh = (dx1 - dx0) / 4.0, (dy1 - dy0) / 4.0
        dboxes = [
            (dx0 + (i % 4) * dw * 0.8, dy0 + (i // 4) * dh * 0.9,
             dx0 + (i % 4) * dw * 0.8 + dw, dy0 + (i // 4) * dh * 0.9 + dh)
            for i in range(N_FUSE)
        ]
        dqueries = [
            f"BBOX(geom, {b[0]}, {b[1]}, {b[2]}, {b[3]})" for b in dboxes
        ]
        distinct_serial = []
        ds.count("gdelt", dqueries[0])  # warm the template's kernel
        t0 = time.time()
        for q in dqueries:
            distinct_serial.append(ds.count("gdelt", q))
        distinct_serial_s = time.time() - t0
        sched = ds.serving.start()
        gate = _threading.Event()
        stall = sched.submit(lambda: gate.wait(30), user="warm", op="stall")
        futs = [
            sched.submit(
                (lambda q=q: ds.count("gdelt", q)),
                user=f"client{i % 4}", op="count",
                fuse=_fuse.make_spec(ds, "count", "gdelt", {"ecql": q}),
            )
            for i, q in enumerate(dqueries)
        ]
        d0 = _disp.value
        t0 = time.time()
        gate.set()
        distinct_fused = [f.result(120) for f in futs]
        distinct_fused_s = time.time() - t0
        stall.result(30)
        distinct_dispatches = _disp.value - d0
        sched.stop()
        assert distinct_fused == distinct_serial, (
            f"distinct fusion NOT bit-identical: "
            f"{distinct_fused[:3]} vs {distinct_serial[:3]}"
        )
        serving_keys.update({
            "distinct_fused_speedup": round(
                distinct_serial_s / max(distinct_fused_s, 1e-9), 2
            ),
            "distinct_fused_dispatches": int(distinct_dispatches),
            "distinct_fused_bit_identical": True,
        })
        sys.stderr.write(
            f"serving: {N_FUSE} DISTINCT-bbox counts serial="
            f"{distinct_serial_s * 1e3:.1f}ms batched="
            f"{distinct_fused_s * 1e3:.1f}ms "
            f"dispatches={distinct_dispatches}\n"
        )

    # Multi-device scale-out (docs/SCALE.md sharded scan + docs/SERVING.md
    # executor pool): with >= 2 local devices, (a) a time-partitioned
    # spill dataset scans serial-vs-sharded — results must match BIT-
    # identically (hard assert) and the speedup rides along with the
    # per-device dispatch counts; (b) serving QPS is measured at pool
    # width 1 vs min(devices, 4). On hosts whose physical cores cannot
    # express 8-way parallelism (the 2-core dev box), the speedup keys
    # are honest-but-flat: "parallel_headroom_limited": true annotates
    # them (the device_unreachable precedent — annotate, never fake), and
    # the CI gate conditions the >1.5x thresholds on headroom while the
    # bit-identity and pool-actually-parallel gates hold everywhere.
    sharded_keys = {}
    if os.environ.get("GEOMESA_BENCH_SHARDED", "1") != "0":
        from geomesa_tpu import config as _scfg
        from geomesa_tpu.index.partitioned import PartitionedFeatureStore

        n_dev = len(jax.devices())
        cores = os.cpu_count() or 1
        sharded_keys["n_devices"] = n_dev
        sharded_keys["parallel_headroom"] = cores
        if cores < 2 * min(n_dev, 4):
            sharded_keys["parallel_headroom_limited"] = True
    if sharded_keys.get("n_devices", 0) >= 2:
        import tempfile as _tempfile

        n_part = min(n, 1_000_000)
        pds = GeoDataset(n_shards=8)
        pds.create_schema("gdelt_p", "weight:Float,dtg:Date,*geom:Point"
                                     ";geomesa.partition='time'")
        pst = pds._store("gdelt_p")
        assert isinstance(pst, PartitionedFeatureStore)
        pst.max_resident = 1
        pst._spill_dir = _tempfile.mkdtemp(prefix="gm_bench_spill_")
        pds.insert("gdelt_p", {k: v[:n_part] for k, v in data.items()},
                   fids=np.arange(n_part).astype(str))
        pds.flush("gdelt_p")

        def _scan_once():
            c = pds.count("gdelt_p", ecql)
            g = pds.density("gdelt_p", ecql, bbox=bbox, width=128,
                            height=128)
            return c, g

        # warm both paths fully (kernels, windows, per-device uploads),
        # then best-of-3 each
        c_sh, g_sh = _scan_once()
        t_sharded = min(_timed(_scan_once) for _ in range(3))
        with _scfg.MESH_DEVICES.scoped("off"):
            c_se, g_se = _scan_once()
            t_serial = min(_timed(_scan_once) for _ in range(3))
        assert c_sh == c_se and np.array_equal(g_sh, g_se), (
            f"sharded scan NOT bit-identical: count {c_sh} vs {c_se}"
        )
        dev_disp = {
            k.rsplit(".", 1)[1]: int(v)
            for k, v in _metrics.registry().report().items()
            if k.startswith(_metrics.SCAN_SHARDED_DEVICE + ".")
        }
        sharded_keys.update({
            "sharded_bit_identical": True,
            "sharded_partitions": len(pst.partition_bins()),
            "sharded_scan_speedup": round(
                t_serial / max(t_sharded, 1e-9), 2
            ),
            "sharded_device_dispatches": dev_disp,
        })
        sys.stderr.write(
            f"sharded scan: {len(pst.partition_bins())} partitions x "
            f"{sharded_keys['n_devices']} devices serial="
            f"{t_serial*1e3:.1f}ms sharded={t_sharded*1e3:.1f}ms "
            f"speedup={sharded_keys['sharded_scan_speedup']}x "
            f"dispatches={dev_disp}\n"
        )

        # serving pool QPS: distinct-bbox counts (fusion can't collapse
        # them) at width 1 vs min(devices, 4); each width warms until
        # every slot has dispatched (per-device executable first-touch)
        pool_w = min(sharded_keys["n_devices"], 4)
        pboxes = [
            f"BBOX(geom, -100, 30, {x}, 45) AND {during}"
            for x in (-95.0, -90.0, -85.0, -80.0)
        ]

        def _pool_qps(width):
            with _scfg.SERVING_EXECUTORS.scoped(str(width)), \
                    _scfg.SERVING_FUSION.scoped("false"):
                s = ds.serving.start()
                try:
                    for _ in range(12):  # warm every slot
                        fs = [
                            s.submit((lambda q: lambda: ds.count(
                                "gdelt", q))(q), user="bench", op="count")
                            for q in pboxes * 2
                        ]
                        [f.result(240) for f in fs]
                        sd = s.snapshot()["slot_dispatches"]
                        if len(sd) == width and min(sd.values()) >= 8:
                            break
                    # per-slot counts persist across start()/stop() on the
                    # dataset's scheduler: report the MEASUREMENT WINDOW's
                    # delta, not warm-up + earlier widths' residue
                    sd0 = dict(s.snapshot()["slot_dispatches"])
                    t0 = time.time()
                    fs = [
                        s.submit((lambda q: lambda: ds.count(
                            "gdelt", q))(q), user="bench", op="count")
                        for q in pboxes * 12
                    ]
                    [f.result(240) for f in fs]
                    dt = time.time() - t0
                    sd1 = s.snapshot()["slot_dispatches"]
                    delta = {
                        k: v - sd0.get(k, 0)
                        for k, v in sd1.items() if v - sd0.get(k, 0) > 0
                    }
                    return len(pboxes) * 12 / max(dt, 1e-9), delta
                finally:
                    s.stop()

        qps_1, _ = _pool_qps(1)
        qps_n, slot_disp = _pool_qps(pool_w)
        sharded_keys.update({
            "pool_executors": pool_w,
            "pool_qps_1": round(qps_1, 1),
            "pool_qps_n": round(qps_n, 1),
            "pool_qps_scaleup": round(qps_n / max(qps_1, 1e-9), 2),
            "pool_slot_dispatches": {
                str(k): int(v) for k, v in sorted(slot_disp.items())
            },
        })
        sys.stderr.write(
            f"serving pool: width 1={qps_1:.1f} qps, width {pool_w}="
            f"{qps_n:.1f} qps (scaleup "
            f"{sharded_keys['pool_qps_scaleup']}x, per-slot {slot_disp})\n"
        )

    # Aggregate-cache effectiveness (docs/CACHE.md): cold vs warm latency
    # with the cache enabled — an exact repeat (whole-result hit) and an
    # overlapping pan (partial-cover reuse: only the newly exposed strip
    # scans). GEOMESA_BENCH_CACHE=0 skips the section.
    cache_keys = {}
    if os.environ.get("GEOMESA_BENCH_CACHE", "1") != "0":
        from geomesa_tpu import config as _cfg

        with _cfg.CACHE_ENABLED.scoped("true"):
            dens_cold = _timed(lambda: ds.density(
                "gdelt", ecql, bbox=bbox, width=W, height=H))
            dens_warm = min(_timed(lambda: ds.density(
                "gdelt", ecql, bbox=bbox, width=W, height=H))
                for _ in range(3))
            cnt_cold = _timed(lambda: ds.count("gdelt", pan_ecql(0.0)))
            # pan east by 2 deg: ~90% overlap with the cold query's cells
            cnt_pan = _timed(lambda: ds.count("gdelt", pan_ecql(2.0)))
        cache_keys = {
            "cache_density_cold_ms": round(dens_cold * 1e3, 2),
            "cache_density_warm_ms": round(dens_warm * 1e3, 2),
            "cache_count_cold_ms": round(cnt_cold * 1e3, 2),
            "cache_count_pan_ms": round(cnt_pan * 1e3, 2),
        }
        sys.stderr.write(
            f"cache: density cold={dens_cold*1e3:.1f}ms "
            f"warm={dens_warm*1e3:.1f}ms | count cold={cnt_cold*1e3:.1f}ms "
            f"pan={cnt_pan*1e3:.1f}ms\n"
        )

        # Hierarchical pre-aggregation (docs/CACHE.md): fine-level quadrant
        # queries warm the level-(k+1) cells, then a domain-spanning
        # zoom-out decomposes over level-k cells. FLAT arm (hierarchy off):
        # every coarse cell misses and scans. HIER arm: coarse cells are
        # pre-merged from the fine cells (bottom-up rollup / on-miss
        # assembly) — the zoom-out must execute ZERO device dispatches and
        # match the uncached full scan bit-for-bit (the smoke-CI gate).
        zoom = f"BBOX(geom, -180, -90, 180, 90) AND {during}"
        quads = [
            f"BBOX(geom, -180, -90, 0, 0) AND {during}",
            f"BBOX(geom, 0, -90, 180, 0) AND {during}",
            f"BBOX(geom, -180, 0, 0, 90) AND {during}",
            f"BBOX(geom, 0, 0, 180, 90) AND {during}",
        ]
        zoom_exact = ds.count("gdelt", zoom)  # cache-disabled oracle
        _disp = _metrics.registry().counter(_metrics.EXEC_DEVICE_DISPATCH)
        import contextlib as _ctx

        # smoke: coarser decomposition (8 coarse / 32 fine cells instead
        # of 64/256) keeps the two warm-up passes inside the CI budget;
        # the gates (zero residual, served fraction, bit-identity) are
        # granularity-independent. The full bench keeps the default.
        _zoom_axis = (_cfg.CACHE_CELLS_PER_AXIS.scoped("4") if smoke
                      else _ctx.nullcontext())
        with _cfg.CACHE_ENABLED.scoped("true"), _zoom_axis:
            with _cfg.CACHE_HIERARCHY.scoped("false"):
                ds.cache.store.invalidate()
                for qq in quads:
                    ds.count("gdelt", qq)
                zoom_flat = _timed(lambda: ds.count("gdelt", zoom))
            ds.cache.store.invalidate()
            for qq in quads:
                ds.count("gdelt", qq)
            d0 = _disp.value
            zoom_n = [None]
            zoom_warm = _timed(lambda: zoom_n.__setitem__(
                0, ds.count("gdelt", zoom)))
            zoom_dispatches = _disp.value - d0
            zev = ds.audit.recent(1)[0]
            zhits, ztotal = map(
                int, zev.hints["exec_path"]["cache_cells"].split("/"))
        assert zoom_n[0] == zoom_exact, (
            f"hierarchy zoom-out NOT bit-identical: {zoom_n[0]} vs "
            f"{zoom_exact}"
        )
        cache_keys.update({
            "cache_zoomout_flat_ms": round(zoom_flat * 1e3, 2),
            "cache_zoomout_warm_ms": round(zoom_warm * 1e3, 2),
            "cache_zoomout_speedup": round(
                zoom_flat / max(zoom_warm, 1e-9), 2
            ),
            "zoomout_zero_residual": zoom_dispatches == 0,
            "hierarchy_served_fraction": round(zhits / max(ztotal, 1), 4),
        })
        sys.stderr.write(
            f"hierarchy: zoom-out flat={zoom_flat*1e3:.1f}ms "
            f"warm={zoom_warm*1e3:.1f}ms "
            f"({cache_keys['cache_zoomout_speedup']}x, "
            f"dispatches={zoom_dispatches}, cells={zhits}/{ztotal})\n"
        )

        # Polygon-region aggregates (docs/CACHE.md): cold = decomposed
        # interior cells + exact boundary scan, warm = whole-result hit.
        # Bit-identity vs the cache-disabled scan is hard-asserted.
        poly = ("POLYGON((-120 26, -84 25, -70 42, -100 48, -122 46, "
                "-120 26))")
        poly_q = f"INTERSECTS(geom, {poly}) AND {during}"
        poly_exact = ds.count("gdelt", poly_q)
        with _cfg.CACHE_ENABLED.scoped("true"):
            pn = [None]
            poly_cold = _timed(lambda: pn.__setitem__(
                0, ds.count("gdelt", poly_q)))
            pw = [None]
            poly_warm = _timed(lambda: pw.__setitem__(
                0, ds.count("gdelt", poly_q)))
        assert pn[0] == poly_exact and pw[0] == poly_exact, (
            f"polygon aggregate NOT bit-identical: cold {pn[0]} warm "
            f"{pw[0]} vs exact {poly_exact}"
        )
        cache_keys.update({
            "cache_polygon_cold_ms": round(poly_cold * 1e3, 2),
            "cache_polygon_warm_ms": round(poly_warm * 1e3, 2),
            "polygon_bit_identical": True,
        })
        sys.stderr.write(
            f"polygon: cold={poly_cold*1e3:.1f}ms "
            f"warm={poly_warm*1e3:.1f}ms (exact n={poly_exact})\n"
        )

    # Standing queries (docs/STANDING.md): many fused subscribers over a
    # hot viewport cost ONE evaluation dispatch per applied ingest batch,
    # the delta-maintained result is bit-identical to the from-scratch
    # re-scan (hard-asserted HERE before the keys print), and the delta
    # update is orders of magnitude cheaper than re-scanning the window.
    # standing_update_p99_ms = p99 of the per-batch standing update pass
    # (every registered group, one dispatch); standing_delta_speedup =
    # full re-scan time over the median delta update.
    standing_keys = {}
    if os.environ.get("GEOMESA_BENCH_STANDING", "1") != "0":
        from geomesa_tpu.subscribe import delta as _sdl

        sub_view = (-100.0, 30.0, -80.0, 45.0)
        sub_ecql = "BBOX(geom, -100, 30, -80, 45)"
        n_watchers = 100
        _sids = [ds.subscribe("gdelt", "count", bbox=sub_view)
                 for _ in range(n_watchers)]
        _sids.append(ds.subscribe("gdelt", "density", bbox=sub_view,
                                  width=256, height=256))
        _eng = ds.standing
        assert len(_eng._groups["gdelt"]) == 2  # 101 watchers, 2 groups

        _srng = np.random.default_rng(17)
        _SB = 2_000
        _sbase = n

        def _sbatch():
            return {
                "geom__x": _srng.uniform(-125, -66, _SB),
                "geom__y": _srng.uniform(24, 49, _SB),
                "dtg": _srng.integers(lo_ms, lo_ms + span_ms, _SB)
                            .astype("datetime64[ms]"),
                "weight": _srng.uniform(0, 1, _SB).astype(np.float32),
            }

        # one-dispatch contract: ONE applied batch -> ONE standing
        # evaluation pass, however many subscribers/groups watch
        _d0 = _metrics.registry().counter(
            _metrics.SUBSCRIBE_DISPATCHES).value
        ds.insert("gdelt", _sbatch(),
                  fids=np.arange(_sbase, _sbase + _SB).astype(str))
        _sbase += _SB
        _disp_delta = _metrics.registry().counter(
            _metrics.SUBSCRIBE_DISPATCHES).value - _d0
        assert _disp_delta == 1, (
            f"hot viewport with {n_watchers + 1} subscribers paid "
            f"{_disp_delta} dispatches for one batch (want 1)"
        )

        # delta timing: the standing update pass over one batch's rows
        # (what the insert observer runs synchronously), vs the
        # from-scratch re-scan of the whole window
        _win = _eng._window_of("gdelt")
        _wcols, _wn = _win.columns()
        _bcols = {k: v[:_SB] for k, v in _wcols.items()}
        _delta_ts = sorted(
            _timed(lambda: _eng.on_batch("gdelt", _bcols, _SB))
            for _ in range(15)
        )
        _rescan_ts = sorted(
            _timed(lambda: _eng.reattach("gdelt")) for _ in range(3)
        )
        # reattach above re-scanned from the real window: the synthetic
        # timing batches are flushed out and bit-identity must hold now
        _wcols, _wn = _win.columns()
        for _grp in _eng._groups["gdelt"].values():
            _fresh, _ = _sdl.eval_rows(_grp.spec, _grp.cf, _win.ft,
                                       _wcols, _wn, _win.dicts)
            assert _sdl.results_equal(_grp.spec, _grp.result, _fresh), (
                "standing result NOT bit-identical to re-scan"
            )
        # cross-check against the device query path too
        _poll = ds.subscription_poll(_sids[0])
        from geomesa_tpu.cache.store import decode_wire_value as _dwv

        assert int(_dwv(_poll["result"])) == int(ds.count("gdelt", sub_ecql))
        _delta_med = _delta_ts[len(_delta_ts) // 2]
        standing_keys = {
            "standing_update_p99_ms": round(
                _delta_ts[min(len(_delta_ts) - 1,
                              int(0.99 * len(_delta_ts)))] * 1e3, 3),
            "standing_delta_speedup": round(
                _rescan_ts[0] / max(_delta_med, 1e-9), 2),
            "standing_one_dispatch": True,
        }
        sys.stderr.write(
            f"standing: {n_watchers + 1} subscribers/2 groups "
            f"delta_p50={_delta_med*1e3:.2f}ms "
            f"rescan={_rescan_ts[0]*1e3:.1f}ms "
            f"speedup={standing_keys['standing_delta_speedup']}x\n"
        )
        for _sid in _sids:
            ds.unsubscribe(_sid)

    # TPU-native spatial join (docs/JOIN.md): cold/warm latency, the
    # candidate-pair pruning fraction on a clustered synthetic (CI gates
    # < 0.2), brute-force bit-identity (hard-asserted HERE, before the
    # line prints), and the recompile-free repeat proof over fresh data.
    # Device baseline note: like every key since BENCH_r04 (rounds 4+),
    # these are CPU(-fallback/mesh) numbers whenever device_unreachable /
    # parallel_headroom_limited apply — the join's accelerator baseline
    # is part of the same open device-baseline gap (ROADMAP bench infra).
    join_keys = {}
    if os.environ.get("GEOMESA_BENCH_JOIN", "1") != "0":
        from geomesa_tpu.kernels import join as _kj
        from geomesa_tpu.planning import join_exec as _jx

        jn = 12_000 if smoke else 30_000
        jm = 10_000 if smoke else 25_000
        _jrng = np.random.default_rng(23)
        _jcx = _jrng.uniform(-150, 150, 24)
        _jcy = _jrng.uniform(-70, 70, 24)

        def _jpts(k):
            _k = _jrng.integers(0, 24, k)
            return (np.clip(_jcx[_k] + _jrng.normal(0, 0.5, k), -179, 179),
                    np.clip(_jcy[_k] + _jrng.normal(0, 0.5, k), -89, 89))

        def _jds_make():
            jds = GeoDataset()
            jds.create_schema("jl", "*geom:Point")
            jds.create_schema("jr", "*geom:Point")
            _lx, _ly = _jpts(jn)
            _rx, _ry = _jpts(jm)
            jds.insert("jl", {"geom": list(zip(_lx, _ly))})
            jds.insert("jr", {"geom": list(zip(_rx, _ry))})
            jds.flush()
            return jds

        _jd = 0.25
        jds = _jds_make()
        t0 = time.perf_counter()
        jres = jds.join("jl", "jr", predicate="dwithin", distance=_jd)
        join_cold_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        jds.join("jl", "jr", predicate="dwithin", distance=_jd)
        join_warm_s = time.perf_counter() - t0
        # bit-identity vs the numpy N*M reference, on the SCANNED row
        # order the join saw (hard assert — the key below records it)
        _p0, _p1 = _kj.pair_params("dwithin", distance=_jd)
        _lb = jds.query("jl").batch
        _rb = jds.query("jr").batch
        _jref = _kj.brute_force_pairs(
            _lb.columns["geom__x"], _lb.columns["geom__y"],
            _rb.columns["geom__x"], _rb.columns["geom__y"],
            "dwithin", _p0, _p1,
        )
        assert jres.count == len(_jref) \
            and np.array_equal(jres.pairs, _jref), \
            "join != brute-force reference"
        # recompile-free repeats: fresh data, same sizes, zero new traces
        _jreg = _jx.join_registry()
        _jt0 = sum(_jreg.traces().values())
        for _ in range(2):
            _jds2 = _jds_make()
            _jds2.join_count("jl", "jr", predicate="dwithin",
                             distance=_jd)
        join_recompiles = sum(_jreg.traces().values()) - _jt0

        # Adaptive per-cell routing A/B (docs/JOIN.md §10). The
        # synthetic MIXES balanced hotspot cells with three heavily
        # piled-up (skewed) ones and a thin uniform background (brute
        # cells) — a uniform synthetic shows no routing win; this mix
        # is the shape the router exists for. Both arms are warmed
        # before timing so the ratio isolates per-cell routing, not
        # compilation, and the adaptive repeat over a FRESH mixed
        # dataset extends the recompile proof across strategy mixes.
        from geomesa_tpu import config as _jcfg

        def _jmix_make():
            sds = GeoDataset()
            sds.create_schema("jl", "*geom:Point")
            sds.create_schema("jr", "*geom:Point")
            _lx, _ly = _jpts(jn)
            _rx, _ry = _jpts(jm)
            _hn = jn // 6
            # pile the extra left rows AWAY from the shared hotspots:
            # there the right side is only the thin uniform background,
            # so these cells are genuinely skewed (split.l), not merely
            # large and balanced
            _skx = np.array([12.3, -60.2, 100.1])
            _sky = np.array([7.9, -33.3, 44.4])
            _hx = np.clip(np.repeat(_skx, _hn)
                          + _jrng.normal(0, 0.05, _hn * 3), -179, 179)
            _hy = np.clip(np.repeat(_sky, _hn)
                          + _jrng.normal(0, 0.05, _hn * 3), -89, 89)
            sds.insert("jl", {"geom": list(zip(
                np.concatenate([_lx, _hx,
                                _jrng.uniform(-170, 170, jn // 10)]),
                np.concatenate([_ly, _hy,
                                _jrng.uniform(-85, 85, jn // 10)])))})
            sds.insert("jr", {"geom": list(zip(
                np.concatenate([_rx,
                                _jrng.uniform(-170, 170, jm // 10)]),
                np.concatenate([_ry,
                                _jrng.uniform(-85, 85, jm // 10)])))})
            sds.flush()
            return sds

        jmx = _jmix_make()
        # warm both arms, then INTERLEAVE the measurements: the two
        # arms drift with the process (allocator state, utilization
        # windows), so back-to-back blocks bias whichever runs second.
        # Median-of-5 alternating rounds cancels the drift.
        _jab = {"true": [], "false": []}
        for _m in ("false", "true"):
            with _jcfg.JOIN_ADAPTIVE.scoped(_m):
                jmx.join_count("jl", "jr", predicate="dwithin",
                               distance=_jd)
        for _ in range(7):
            for _m in ("false", "true"):
                with _jcfg.JOIN_ADAPTIVE.scoped(_m):
                    _jab[_m].append(_timed(lambda: jmx.join_count(
                        "jl", "jr", predicate="dwithin", distance=_jd)))
        # min, not mean: the best observed run is the cleanest estimate
        # of each arm's intrinsic cost under scheduler/allocator noise
        t_single = float(min(_jab["false"]))
        t_adapt = float(min(_jab["true"]))
        jad = jmx.join("jl", "jr", predicate="dwithin", distance=_jd)
        _scells = dict(jad.stats.strategy_cells)
        with _jcfg.JOIN_ADAPTIVE.scoped("false"):
            jsg = jmx.join("jl", "jr", predicate="dwithin", distance=_jd)

        def _jdp(st):
            dp = st.dispatched_pairs
            return sum(dp.values()) if isinstance(dp, dict) else int(dp)

        # deterministic counterpart to the wall-clock ratio: padded
        # kernel slots the router avoided dispatching. Wall-clock on a
        # shared-core CPU mesh is launch-overhead-bound and noisy; the
        # slot ratio is the structural win that scales with accelerator
        # arithmetic throughput (docs/JOIN.md §10).
        join_dispatch_ratio = round(_jdp(jsg.stats) / max(_jdp(jad.stats), 1), 3)
        # fresh mixed dataset, same sizes: the adaptive router must not
        # pay a single new trace whatever strategies the cells land on
        _jt1 = sum(_jreg.traces().values())
        _jmix_make().join_count("jl", "jr", predicate="dwithin",
                                distance=_jd)
        join_recompiles += sum(_jreg.traces().values()) - _jt1

        # Polygon-dataset join: cold latency and bit-identity vs the
        # N*M point-in-polygon reference (holes + multipolygon).
        pds = GeoDataset()
        pds.create_schema("pts", "*geom:Point")
        pds.create_schema("polys", "*geom:Polygon")
        _pn = 3_000 if smoke else 8_000
        pds.insert("pts", {"geom": list(zip(
            _jrng.uniform(-40, 70, _pn), _jrng.uniform(-30, 45, _pn)))})
        pds.insert("polys", {"geom": np.array([
            "POLYGON ((0 0, 30 0, 30 30, 0 30, 0 0),"
            " (10 10, 20 10, 20 20, 10 20, 10 10))",
            "MULTIPOLYGON (((-30 -10, -20 -10, -20 0, -30 0, -30 -10)),"
            " ((40 20, 55 20, 55 35, 40 35, 40 20)))",
        ], object)})
        pds.flush()
        t0 = time.perf_counter()
        pres = pds.join("pts", "polys", predicate="pip")
        join_poly_cold_s = time.perf_counter() - t0
        _pb = pds.query("pts").batch
        from geomesa_tpu.utils import geometry as _geo

        _pg = [_geo.parse_wkt(str(w)) for w in
               pds.query("polys").batch.columns["geom__wkt"]]
        _pref = _kj.polygon_brute_force(
            _pb.columns["geom__x"], _pb.columns["geom__y"], _pg, "pip")
        join_poly_identical = bool(
            pres.count == len(_pref) and np.array_equal(pres.pairs, _pref))
        assert join_poly_identical, "polygon join != brute-force reference"

        # Window-pushdown side scan over a spilled partitioned right
        # side: the fraction of side bytes the footer statistics let
        # the count-only join skip (docs/JOIN.md §10, docs/LAKE.md).
        import contextlib as _ctx
        import shutil as _sh
        import tempfile as _tf

        from geomesa_tpu.api.dataset import Query as _Q
        from geomesa_tpu.filter.ecql import parse_iso_ms as _iso

        _pdir = _tf.mkdtemp(prefix="bench-join-push-")
        try:
            with _ctx.ExitStack() as _stk:
                _stk.enter_context(_jcfg.LAKE_ENABLED.scoped("true"))
                _stk.enter_context(_jcfg.LAKE_ROWGROUP_ROWS.scoped("512"))
                wds = GeoDataset(n_shards=4)
                wds.create_schema(
                    "t", "dtg:Date,*geom:Point;geomesa.partition='time'")
                _wst = wds._store("t")
                _wst._spill_dir = _pdir
                _wn = 20_000 if smoke else 60_000
                _wk = _jrng.integers(0, 10, _wn)
                _wcx = _jrng.uniform(-115, -75, 10)
                _wcy = _jrng.uniform(28, 47, 10)
                wds.insert("t", {
                    "dtg": _jrng.integers(
                        _iso("2020-01-01"), _iso("2020-02-01"),
                        _wn).astype("datetime64[ms]"),
                    "geom__x": np.clip(
                        _wcx[_wk] + _jrng.normal(0, 0.25, _wn), -120, -70),
                    "geom__y": np.clip(
                        _wcy[_wk] + _jrng.normal(0, 0.25, _wn), 25, 50),
                })
                wds.flush()
                _wst.spill_all()
            wds.create_schema("pts", "*geom:Point")
            # the left viewport covers a subset of the side's hotspots
            _wk = _jrng.integers(0, 4, 600)
            wds.insert("pts", {"geom": list(zip(
                np.clip(_wcx[_wk] + _jrng.normal(0, 0.2, 600), -120, -70),
                np.clip(_wcy[_wk] + _jrng.normal(0, 0.2, 600), 25, 50)))})
            wds.flush()
            _, _, _, _, _wtotal, _wstats = wds._join_pushdown_count(
                "pts", "t", "dwithin", 0.1, None, None, _Q(), _Q(),
                None, False)
            with _jcfg.JOIN_PUSHDOWN.scoped("false"):
                assert _wtotal == wds.join_count(
                    "pts", "t", predicate="dwithin", distance=0.1), \
                    "pushdown side scan != full materialization"
            _wpd = _wstats.pushdown
            join_side_fraction = round(
                _wpd["bytes_loaded"] / max(_wpd["bytes_side"], 1), 4)
        finally:
            _sh.rmtree(_pdir, ignore_errors=True)

        join_keys = {
            "join_cold_ms": round(join_cold_s * 1e3, 2),
            "join_warm_ms": round(join_warm_s * 1e3, 2),
            "join_candidate_fraction": round(
                jres.stats.candidate_fraction, 4
            ),
            "join_bit_identical": True,
            "join_recompiles": int(join_recompiles),
            "join_matched": int(jres.count),
            "join_devices": int(jres.stats.devices),
            "join_adaptive_speedup": round(t_single / max(t_adapt, 1e-9), 3),
            "join_adaptive_dispatch_ratio": join_dispatch_ratio,
            "join_adaptive_cells_split": int(
                _scells.get("split.l", 0) + _scells.get("split.r", 0)),
            "join_adaptive_cells_brute": int(_scells.get("brute", 0)),
            "join_polygon_cold_ms": round(join_poly_cold_s * 1e3, 2),
            "join_polygon_bit_identical": join_poly_identical,
            "join_side_bytes_fraction": join_side_fraction,
        }
        if cpu_backend or annotations.get("device_unreachable") \
                or sharded_keys.get("parallel_headroom_limited"):
            join_keys["join_device_baseline"] = (
                "cpu-fallback (parallel_headroom_limited)"
                if sharded_keys.get("parallel_headroom_limited")
                else "cpu-fallback"
            )
        sys.stderr.write(
            f"join: cold={join_cold_s*1e3:.1f}ms "
            f"warm={join_warm_s*1e3:.1f}ms "
            f"matched={jres.count} "
            f"cand_frac={jres.stats.candidate_fraction:.4f} "
            f"recompiles={join_recompiles} "
            f"adaptive_speedup={t_single / max(t_adapt, 1e-9):.2f}x "
            f"dispatch_ratio={join_dispatch_ratio}x "
            f"cells={_scells} "
            f"poly_cold={join_poly_cold_s*1e3:.1f}ms "
            f"side_bytes_frac={join_side_fraction}\n"
        )

    # Columnar geo-lake tier (docs/LAKE.md): lake-vs-npz scan
    # bit-identity (hard-asserted before the keys print), the selective
    # cold-scan pushdown fraction (CI gates < 0.3), the lake-backed warm
    # path's recompile count (CI gates 0), and the cache
    # persist/restore round trip (restore must answer a warm zoom-out
    # with ZERO device dispatches).
    lake_keys = {}
    if os.environ.get("GEOMESA_BENCH_LAKE", "1") != "0":
        import shutil as _shutil
        import tempfile as _tempfile

        from geomesa_tpu import config as _cfg
        from geomesa_tpu import metrics as _metrics
        from geomesa_tpu.lake.snapshot import PartitionSnapshot as _PSnap

        _lspec = ("name:String,weight:Double,dtg:Date,*geom:Point"
                  ";geomesa.partition='time'")
        _ln = 30_000 if smoke else 150_000
        _lrng = np.random.default_rng(29)
        _lcx = _lrng.uniform(-115, -75, 10)
        _lcy = _lrng.uniform(28, 47, 10)
        _lk = _lrng.integers(0, 10, _ln)
        _lo = np.datetime64("2020-01-01", "ms").astype(np.int64)
        _ldata = {
            "name": [f"a{i % 20}" for i in range(_ln)],
            "weight": _lrng.uniform(0, 10, _ln),
            "dtg": (_lo + _lrng.integers(0, 31 * 86_400_000, _ln)
                    ).astype("datetime64[ms]"),
            "geom__x": np.clip(
                _lcx[_lk] + _lrng.normal(0, 0.25, _ln), -120, -70),
            "geom__y": np.clip(
                _lcy[_lk] + _lrng.normal(0, 0.25, _ln), 25, 50),
        }
        _lake_dir = _tempfile.mkdtemp(prefix="gm-lake-bench-")

        def _lds_make(lake_on):
            with _cfg.LAKE_ENABLED.scoped("true" if lake_on else "false"), \
                    _cfg.LAKE_ROWGROUP_ROWS.scoped("512"):
                lds = GeoDataset(n_shards=4)
                lds.create_schema("lt", _lspec)
                lst = lds._store("lt")
                lst._spill_dir = os.path.join(
                    _lake_dir, "lake" if lake_on else "npz")
                lds.insert("lt", _ldata,
                           fids=np.arange(_ln).astype(str))
                lds.flush()
                lst.spill_all()
            return lds, lst

        _lds, _lst = _lds_make(True)
        _nds, _nst = _lds_make(False)
        _hx = float(_ldata["geom__x"][0])
        _hy = float(_ldata["geom__y"][0])
        _lsel = (f"BBOX(geom, {_hx - 0.4}, {_hy - 0.4}, "
                 f"{_hx + 0.4}, {_hy + 0.4})")
        _lbt = (f"BBOX(geom, {_hx - 2}, {_hy - 2}, {_hx + 2}, {_hy + 2})"
                " AND dtg DURING "
                "2020-01-05T00:00:00Z/2020-01-20T00:00:00Z")
        with _cfg.LAKE_ENABLED.scoped("true"):
            # bit-identity: every additive op, npz vs lake (hard assert)
            for _q in (_lsel, _lbt, "INCLUDE"):
                assert _lds.count("lt", _q) == _nds.count("lt", _q), \
                    f"lake != npz count for {_q!r}"
            _lbox = (-120, 25, -70, 50)
            assert np.array_equal(
                _lds.density("lt", _lbt, _lbox, 64, 32),
                _nds.density("lt", _lbt, _lbox, 64, 32),
            ), "lake != npz density"
            _lcv = _lds.density_curve("lt", _lbt, level=6)
            _ncv = _nds.density_curve("lt", _lbt, level=6)
            assert np.array_equal(_lcv[0], _ncv[0]), "lake != npz curve"
            assert (_lds.stats("lt", "MinMax(weight)", _lbt).to_json()
                    == _nds.stats("lt", "MinMax(weight)", _lbt).to_json()
                    ), "lake != npz stats"

            # selective cold scan: pushdown fraction + latency (total
            # AFTER spill_all — the identity queries above re-admitted
            # partitions to residency, emptying the spilled map)
            _lst.spill_all()
            _ltotal = sum(_PSnap(d).payload_bytes(None)
                          for d in _lst.spilled.values()) or 1
            _skip0 = _metrics.registry().counter(
                "lake.bytes.skipped").value
            t0 = time.perf_counter()
            _lds.count("lt", _lsel)
            lake_cold_selective_s = time.perf_counter() - t0
            _lskip = _metrics.registry().counter(
                "lake.bytes.skipped").value - _skip0
            lake_fraction = 1.0 - _lskip / _ltotal

            # lake-backed warm path: re-loading spilled lake partitions
            # and re-running the same query must compile NOTHING new
            _lst.spill_all()
            _rc0 = _metrics.registry().counter("kernel.recompiles").value
            _lds.count("lt", _lsel)
            lake_recompiles = int(
                _metrics.registry().counter("kernel.recompiles").value
                - _rc0)

        # cache persistence: warm zoom-out -> persist -> fresh process
        # (load) -> restore -> the warm zoom answers with ZERO dispatches
        with _cfg.CACHE_ENABLED.scoped("true"), \
                _cfg.CACHE_CELLS_PER_AXIS.scoped("4"):
            _cds = GeoDataset(n_shards=2)
            _cds.create_schema("ct", "weight:Double,dtg:Date,*geom:Point")
            _cn = 6_000
            _cds.insert("ct", {
                "weight": _lrng.uniform(0, 2, _cn),
                "dtg": np.full(_cn, _lo).astype("datetime64[ms]"),
                "geom__x": _lrng.uniform(-170, 170, _cn),
                "geom__y": _lrng.uniform(-80, 80, _cn),
            }, fids=np.arange(_cn).astype(str))
            _cds.flush()
            for _q in ("BBOX(geom, -90, -45, 0, 0)",
                       "BBOX(geom, 0, -45, 90, 0)",
                       "BBOX(geom, -90, 0, 0, 45)",
                       "BBOX(geom, 0, 0, 90, 45)"):
                _cds.count("ct", _q)
            _zoom = "BBOX(geom, -90, -45, 90, 45)"
            _zref = _cds.count("ct", _zoom)
            _ckpt = os.path.join(_lake_dir, "ckpt")
            _cpath = os.path.join(_lake_dir, "cache.lake")
            _cds.save(_ckpt)
            t0 = time.perf_counter()
            _cds.persist_cache(_cpath)
            _cds2 = GeoDataset.load(_ckpt)
            _rsum = _cds2.restore_cache(_cpath)
            cache_persist_restore_s = time.perf_counter() - t0
            assert _rsum["ct"].get("restored", 0) > 0, \
                "cache restore admitted nothing"
            _d0 = _metrics.registry().counter(
                "exec.device.dispatch").value
            assert _cds2.count("ct", _zoom) == _zref, \
                "restored zoom-out != warm answer"
            cache_restore_dispatches = int(
                _metrics.registry().counter(
                    "exec.device.dispatch").value - _d0)
            assert cache_restore_dispatches == 0, \
                "restored warm zoom-out dispatched to the device"

        _shutil.rmtree(_lake_dir, ignore_errors=True)
        lake_keys = {
            "lake_cold_selective_ms": round(
                lake_cold_selective_s * 1e3, 2),
            "lake_bytes_loaded_fraction": round(lake_fraction, 4),
            "lake_bit_identical": True,
            "lake_warm_recompiles": lake_recompiles,
            "cache_persist_restore_ms": round(
                cache_persist_restore_s * 1e3, 2),
            "cache_restore_dispatches": cache_restore_dispatches,
        }
        sys.stderr.write(
            f"lake: selective_cold={lake_cold_selective_s*1e3:.1f}ms "
            f"bytes_loaded_fraction={lake_fraction:.4f} "
            f"warm_recompiles={lake_recompiles} "
            f"persist_restore={cache_persist_restore_s*1e3:.1f}ms\n"
        )

    # Observability snapshot (docs/OBSERVABILITY.md): the perf trajectory
    # carries the registry's warm-path/cache/pipeline counters and the
    # query-stage latency distribution, so a regression in ANY of them is
    # visible in the BENCH_*.json history without re-running anything.
    _report = _metrics.registry().report()

    def _metric(name, default=0):
        v = _report.get(name, default)
        return round(v, 4) if isinstance(v, float) else v

    _scan_hist = _metrics.registry().timer("query.density").hist
    from geomesa_tpu import utilization as _util

    _usnap = _util.snapshot()
    # per-device attributed busy seconds (the device.busy.<id> gauges'
    # totals). NOTE: like every key in this file since BENCH_r04/r05,
    # these are CPU(-mesh) numbers when device_unreachable is set — the
    # accelerator utilization baseline is still an open gap.
    _dev_busy = {
        k: v["busy_s"] for k, v in _usnap["devices"].items()
    }
    _cost_rollup = {}
    for _led in ds.serving.user_rollups().values():
        for _k, _v in _led.get("cost", {}).items():
            _cost_rollup[_k] = round(_cost_rollup.get(_k, 0.0) + _v, 4)
    metrics_snapshot = {
        "kernel_recompiles": _metric("kernel.recompiles"),
        "kernel_bucket_hit": _metric("kernel.bucket_hit"),
        "kernel_evict": _metric("kernel.evict"),
        # recompiles paid for keys the LRU had previously evicted: the
        # registry-pressure signal (docs/PERF.md "Registry pressure" —
        # nonzero means geomesa.kernel.cache.size is too small for the
        # live working set)
        "eviction_recompiles": _metric("kernel.recompiles.evicted"),
        "kernel_recompile_alerts": _metric("kernel.recompile.alerts"),
        "serving_fused_distinct": _metric("serving.fused.distinct"),
        "pipeline_prefetch": _metric("pipeline.prefetch"),
        "cache_hit": _metric("cache.hit"),
        "cache_partial": _metric("cache.partial"),
        "cache_miss": _metric("cache.miss"),
        "cache_hierarchy_hit": _metric("cache.hierarchy.hit"),
        "cache_hierarchy_promote": _metric("cache.hierarchy.promote"),
        "cache_hierarchy_residual": _metric("cache.hierarchy.residual"),
        "cache_polygon": _metric("cache.polygon"),
        "serving_fused": _metric("serving.fused"),
        "serving_shed": _metric("serving.shed.deadline"),
        "device_dispatches": _metric("exec.device.dispatch"),
        "density_p50_ms": round(_scan_hist.quantile(0.5) * 1e3, 3),
        "density_p99_ms": round(_scan_hist.quantile(0.99) * 1e3, 3),
        "trace_export_exported": _metric("trace.export.exported"),
        "trace_export_dropped": _metric("trace.export.dropped"),
        # busiest device's trailing-window fraction (0 when the window
        # has rolled past the measurement — totals are in device_busy)
        "device_busy_fraction": max(
            [v["busy_fraction"] for v in _usnap["devices"].values()],
            default=0.0,
        ),
        # per-user cost attribution summed over the serving ledger:
        # device_ms.<id>, partitions_scanned/pruned, bytes_staged,
        # cache_hits, recompiles (docs/OBSERVABILITY.md)
        "cost_ledger": _cost_rollup,
        # adaptive-join routing histogram: cells handled per strategy
        # across every join in the run (join.cells.<strategy> counters,
        # docs/JOIN.md §10) + total side bytes the pushdown scans paid
        "join_cells_strategy": {
            k[len(_metrics.JOIN_CELLS_STRATEGY):]: v
            for k, v in _report.items()
            if k.startswith(_metrics.JOIN_CELLS_STRATEGY)
        },
        "join_pushdown_bytes": _metric(_metrics.JOIN_PUSHDOWN_BYTES),
    }

    feats_per_sec = n / dev_s
    speedup = cpu_s / dev_s
    scanned = int(plan.__dict__.get("scanned_rows", 0))
    sys.stderr.write(
        f"n={n} gen={gen_s:.1f}s ingest={ingest_s:.1f}s matched={matched:.0f} "
        f"scanned={scanned} device={dev_s*1e3:.1f}ms cpu={cpu_s*1e3:.1f}ms "
        f"speedup={speedup:.1f}x p50_e2e_density={p50_e2e_ms:.1f}ms\n"
    )
    # One line, both headline metrics (BASELINE.md): kernel throughput is
    # the headline value; p50 e2e density latency + selectivity counters
    # ride along so README/SCALE.md claims are driver-checkable.
    print(json.dumps({
        "metric": "bbox_time_density_scan_throughput",
        "value": round(feats_per_sec, 1),
        "unit": "features/sec",
        "vs_baseline": round(speedup, 2),
        "p50_e2e_density_ms": round(p50_e2e_ms, 2),
        "device_ms": round(dev_s * 1e3, 3),
        "cpu_ms": round(cpu_s * 1e3, 1),
        "n_rows": n,
        "rows_scanned": scanned,
        "rows_matched": int(matched),
        "ingest_s": round(ingest_s, 1),
        "warm_requery_ms": round(warm_requery_ms, 2),
        "recompiles_per_100_queries": round(recompiles_per_100, 1),
        "trace_overhead_pct": round(trace_overhead_pct, 2),
        "export_overhead_pct": round(export_overhead_pct, 2),
        "export_path": export_path,
        "device_busy": _dev_busy,
        "metrics": metrics_snapshot,
        **serving_keys,
        **sharded_keys,
        **cache_keys,
        **standing_keys,
        **join_keys,
        **lake_keys,
        **annotations,
        **_device_baseline(
            "forced-cpu-mesh (smoke)" if smoke
            else "device-unreachable"
            if annotations.get("device_unreachable") else None
        ),
    }))


if __name__ == "__main__":
    main()
