"""Benchmark: bbox+time CQL filter + density heatmap throughput.

The north-star configuration (BASELINE.md): features/sec on a spatio-temporal
filter + density aggregation, device vs single-threaded-process numpy CPU
baseline (the reference provides no published numbers; the CPU path here IS
the measured baseline, per BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Env knobs: GEOMESA_BENCH_N (points, default 20M), GEOMESA_BENCH_ITERS.
"""

import json
import os
import sys
import time

import numpy as np


def main():
    n = int(os.environ.get("GEOMESA_BENCH_N", 20_000_000))
    iters = int(os.environ.get("GEOMESA_BENCH_ITERS", 10))

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from geomesa_tpu import GeoDataset
    from geomesa_tpu.filter.ecql import parse_iso_ms

    rng = np.random.default_rng(7)
    t0 = time.time()
    # GDELT-like point events across CONUS over one month
    data = {
        "geom__x": rng.uniform(-125, -66, n),
        "geom__y": rng.uniform(24, 49, n),
        "dtg": rng.integers(
            parse_iso_ms("2020-01-01"), parse_iso_ms("2020-02-01"), n
        ).astype("datetime64[ms]"),
        "weight": rng.uniform(0, 1, n).astype(np.float32),
    }
    gen_s = time.time() - t0

    ds = GeoDataset(n_shards=8)
    ds.create_schema("gdelt", "weight:Float,dtg:Date,*geom:Point")
    t0 = time.time()
    ds.insert("gdelt", data, fids=np.arange(n).astype(str))
    ds.flush("gdelt")
    ingest_s = time.time() - t0

    ecql = (
        "BBOX(geom, -100, 30, -80, 45) AND "
        "dtg DURING 2020-01-05T00:00:00Z/2020-01-15T00:00:00Z"
    )
    bbox = (-100.0, 30.0, -80.0, 45.0)
    W = H = 512

    # plan once; executor caches the jitted kernel on the plan
    st, _, plan = ds._plan("gdelt", ecql)
    ex = ds._executor(st)

    # device path: warmup (compile + window upload) then steady-state.
    # Results stay on device inside the loop (as in a real pipeline where
    # grids feed further device-side composition or ride PCIe); the best
    # iteration is reported to reject host-link latency spikes, which on
    # tunneled dev setups can exceed the kernel time by 100x.
    import jax

    grid_dev = ex.density(plan, bbox, W, H, as_numpy=False)
    jax.block_until_ready(grid_dev)
    # batch async dispatches inside the timed region so the (tunneled) host
    # sync cost is amortized 1/BATCH — per-call tunnel jitter previously
    # swamped the ~0.25ms kernel and made rounds incomparable
    batch = int(os.environ.get("GEOMESA_BENCH_BATCH", 8))
    dev_s = float("inf")
    for _ in range(iters):
        t0 = time.time()
        for _ in range(batch):
            grid_dev = ex.density(plan, bbox, W, H, as_numpy=False)
        jax.block_until_ready(grid_dev)
        dev_s = min(dev_s, (time.time() - t0) / batch)
    grid = np.asarray(grid_dev)
    matched = float(grid.sum())

    # CPU baseline: vectorized numpy over the same raw arrays (filter + 2D hist)
    x, y = data["geom__x"], data["geom__y"]
    t = data["dtg"].astype(np.int64)
    lo, hi = parse_iso_ms("2020-01-05"), parse_iso_ms("2020-01-15")
    t0 = time.time()
    cpu_iters = max(1, min(3, iters))
    for _ in range(cpu_iters):
        m = (
            (x >= bbox[0]) & (x <= bbox[2]) & (y >= bbox[1]) & (y <= bbox[3])
            & (t >= lo) & (t <= hi)
        )
        px = np.clip(((x[m] - bbox[0]) / (bbox[2] - bbox[0]) * W).astype(np.int64), 0, W - 1)
        py = np.clip(((y[m] - bbox[1]) / (bbox[3] - bbox[1]) * H).astype(np.int64), 0, H - 1)
        cpu_grid = np.zeros(H * W, np.float32)
        np.add.at(cpu_grid, py * W + px, 1.0)
    cpu_s = (time.time() - t0) / cpu_iters

    assert abs(matched - float(m.sum())) <= max(1.0, 1e-5 * n), (
        f"device {matched} vs cpu {float(m.sum())}"
    )

    feats_per_sec = n / dev_s
    speedup = cpu_s / dev_s
    sys.stderr.write(
        f"n={n} gen={gen_s:.1f}s ingest={ingest_s:.1f}s matched={matched:.0f} "
        f"device={dev_s*1e3:.1f}ms cpu={cpu_s*1e3:.1f}ms speedup={speedup:.1f}x\n"
    )
    print(json.dumps({
        "metric": "bbox_time_density_scan_throughput",
        "value": round(feats_per_sec, 1),
        "unit": "features/sec",
        "vs_baseline": round(speedup, 2),
    }))


if __name__ == "__main__":
    main()
