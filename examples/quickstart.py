"""End-to-end quickstart: converter ingest -> indexed store -> queries ->
pushdown analytics -> export -> checkpoint.

Run it (CPU backend works everywhere; on a TPU host just drop the env):

    JAX_PLATFORMS=cpu python examples/quickstart.py

Every step mirrors a reference GeoMesa workflow (the geomesa-tutorials
GDELT walk-through): same converter config shape, same ECQL, same
analytic surface — re-based on TPU-shaped kernels.
"""

import os
import tempfile

import numpy as np

from geomesa_tpu import GeoDataset, Query

# -- 1. schema + converter config (geomesa-convert HOCON shape) -----------

SPEC = "event:String:index=true,score:Float,dtg:Date,*geom:Point"

CONVERTER = {
    "type": "delimited-text",
    "format": "CSV",
    "id-field": "$1",
    "options": {"skip-lines": 1},
    "fields": [
        {"name": "event", "transform": "$2"},
        {"name": "score", "transform": "toDouble($3)"},
        {"name": "dtg", "transform": "date('yyyy-MM-dd', $4)"},
        {"name": "geom", "transform": "point(toDouble($5), toDouble($6))"},
    ],
}


def synthesize_csv(n: int = 200_000, seed: int = 7) -> str:
    rng = np.random.default_rng(seed)
    days = rng.integers(1, 28, n)
    rows = ["id,event,score,date,lon,lat"]
    events = np.asarray(["protest", "meeting", "aid", "statement"])
    ev = events[rng.integers(0, 4, n)]
    lon = rng.uniform(-125, -66, n)
    lat = rng.uniform(24, 49, n)
    sc = rng.uniform(0, 10, n)
    for i in range(n):
        rows.append(
            f"e{i},{ev[i]},{sc[i]:.3f},2020-01-{days[i]:02d},"
            f"{lon[i]:.5f},{lat[i]:.5f}"
        )
    return "\n".join(rows)


def main():
    ds = GeoDataset(n_shards=8)
    ds.create_schema("gdelt", SPEC)

    # -- 2. ingest ---------------------------------------------------------
    ctx = ds.ingest("gdelt", synthesize_csv(), CONVERTER)
    print(f"ingested: {ctx.success} ok, {ctx.failure} rejected")

    # -- 3. ECQL queries ---------------------------------------------------
    ecql = (
        "BBOX(geom, -100, 30, -80, 45) AND "
        "dtg DURING 2020-01-05T00:00:00Z/2020-01-15T00:00:00Z AND "
        "event = 'protest'"
    )
    print("count:", ds.count("gdelt", ecql))
    print(ds.explain("gdelt", ecql).splitlines()[0])

    top = ds.query("gdelt", Query(
        ecql=ecql, sort_by=[("score", True)], max_features=3,
        properties=["score"],
    ))
    print("top scores:", np.round(np.asarray(top.columns["score"], float), 2))

    # -- 4. pushdown analytics --------------------------------------------
    grid = ds.density("gdelt", ecql, bbox=(-100, 30, -80, 45),
                      width=256, height=256)
    print("density grid:", grid.shape, "sum", int(grid.sum()))

    tile, snapped = ds.density_curve("gdelt", ecql, level=8)
    print("curve-aligned tile:", tile.shape, "bbox", [round(v, 2) for v in snapped])

    stats = ds.stats("gdelt", "MinMax(score);Histogram(score,10,0,10)", ecql)
    print("stats:", stats.to_json()[:80], "...")

    knn = ds.knn("gdelt", x=-90.0, y=38.5, k=5)
    print("knn fids:", knn.fids)

    # -- 5. export + checkpoint -------------------------------------------
    from geomesa_tpu.io import geojson

    st = ds._store("gdelt")
    fc = ds.query("gdelt", Query(ecql=ecql, max_features=2))
    print("geojson head:", geojson.dumps(st.ft, fc.batch, st.dicts)[:90], "...")

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "catalog")
        ds.save(path)
        ds2 = GeoDataset.load(path)
        assert ds2.count("gdelt", ecql) == ds.count("gdelt", ecql)
        print("checkpoint round-trip OK")

    # -- 6. round-5 surfaces ----------------------------------------------
    # expression comparisons: property-vs-property, arithmetic, st_* calls
    n_expr = ds.count("gdelt", ecql + " AND score * 2 > 10")
    n_fn = ds.count(
        "gdelt",
        "st_distanceSphere(geom, st_geomFromWKT('POINT (-90 38)')) < 300000")
    print(f"expression filters: score*2>10 -> {n_expr}, "
          f"within 300km of (-90,38) -> {n_fn}")

    # device top-k sort pushdown (threshold select): multi-key sorts stay
    # exact — the device gathers primary-key candidates with boundary
    # ties, the host finishes the lexicographic order
    top = ds.query("gdelt", Query(
        ecql=ecql, sort_by=[("event", False), ("score", True)],
        max_features=3))
    print("top-3 by (event asc, score desc):",
          list(zip(top.to_dict()["event"],
                   [round(float(v), 2) for v in top.columns["score"]])))

    # live index lifecycle: enable an attribute index without recreating
    ds.add_attribute_index("gdelt", "score")
    print(ds.describe("gdelt").splitlines()[-1].strip())

    # CRS: results in web mercator (closed-form; UTM/5070/3035 also built in)
    merc = ds.query("gdelt", Query(ecql=ecql, max_features=1, srid=3857))
    print("EPSG:3857 x:", round(float(merc.columns["geom__x"][0]), 1))


if __name__ == "__main__":
    main()
